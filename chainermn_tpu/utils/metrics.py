"""Metrics & SLO layer — a low-overhead registry of counters, gauges
and latency histograms with cross-rank merge and fleet exposition.

The flight recorder (:mod:`chainermn_tpu.utils.telemetry`) answers
*"what happened, when"* — a timeline of span events.  Nothing in the
stack turned those timestamps into *distributions*: ``bench_serving``
recomputed TTFT percentiles ad-hoc with numpy, ``StragglerReport``
allgathered per-phase *means* only, and no component exposed anything
a fleet scraper could read.  This module is the distribution layer:

- :class:`Counter` — monotonic total (requests admitted, snapshots
  written, stalls).  Cross-rank merge is a sum.
- :class:`Gauge` — last-set value plus the max it ever held (queue
  depth, goodput).  Cross-rank merge keeps max-of-max and max-of-last.
- :class:`Histogram` — a latency distribution over a FIXED log-spaced
  bucket lattice shared by every histogram in every process
  (:data:`LATTICE_EDGES`), so cross-rank merge is a bucket-wise sum —
  no quantile sketches to reconcile, no per-rank boundary drift.
  Below :attr:`~Histogram.sample_cap` observations the raw samples are
  retained too, so small-n percentiles are EXACT (numpy-identical
  linear interpolation); past the cap, p50/p9x come from interpolated
  bucket quantiles (error bounded by one bucket's width, a factor of
  ``10^(1/8) ≈ 1.33``).
- :class:`MetricsRegistry` — the process-global name→instrument table
  with the same enabled/disabled discipline as ``TraceRecorder``:
  disabled, every record call is an early return and the instrument
  getters hand back ONE shared no-op singleton (allocation-free,
  pinned by test).  ``CHAINERMN_TPU_METRICS=1`` enables at import.
- :func:`merge_metrics` — ``allgather_obj`` every rank's snapshot and
  fold: counters sum, gauges max, histograms bucket-sum, divergent
  name sets union (the PR 6 ``ObservationAggregator`` convention).
  The rows arrive rank-ordered and the fold is deterministic, so every
  rank computes ONE identical merged snapshot.
- Exposition: :func:`to_prometheus` (node-exporter textfile
  convention — ``# TYPE`` headers, cumulative ``_bucket{le=...}``
  rows, label sets such as ``rank="0"``; :func:`export_prometheus`
  writes it atomically) and :func:`export_jsonl` (append-one-line
  snapshots for dashboards).  :func:`parse_prometheus_text` /
  :func:`histogram_from_prometheus` close the round trip.

Trainer extensions: :class:`GoodputReport` decomposes window wall time
into productive compute vs checkpoint / exchange-probe / host-blocked
/ stall badput by draining the flight recorder's phase stats, and
:class:`MetricsTextfile` flushes the (optionally cross-rank merged)
registry to ``<out>/metrics.prom`` on trigger.

This module must stay importable without jax: :mod:`telemetry` (which
the iterator layer imports) builds its per-phase histograms on the
shared lattice here, and everything jax-flavoured (``merge_metrics``'s
communicator, ``GoodputReport``'s recorder) is resolved lazily.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "GoodputReport",
    "Histogram",
    "LATTICE_EDGES",
    "MetricsRegistry",
    "MetricsTextfile",
    "append_jsonl",
    "export_jsonl",
    "export_prometheus",
    "get_registry",
    "histogram_from_prometheus",
    "merge_metrics",
    "parse_prometheus_text",
    "set_registry",
    "to_prometheus",
]

# ---------------------------------------------------------------------- #
# the shared bucket lattice
# ---------------------------------------------------------------------- #

# Fixed log-spaced upper edges from 100 ns to 100 ks, 8 buckets per
# decade.  FIXED is the point: every histogram in every process buckets
# against the same edges, so a cross-rank (or cross-run) merge is a
# plain bucket-wise sum.  The range covers everything this stack
# times — a µs-scale counter bump to a day-scale training window —
# and 8/decade bounds interpolated-quantile error at 10^(1/8) ≈ 1.33×.
_LAT_LO_EXP = -7
_LAT_HI_EXP = 5
_LAT_PER_DECADE = 8

LATTICE_EDGES: tuple = tuple(
    10.0 ** (_LAT_LO_EXP + i / _LAT_PER_DECADE)
    for i in range((_LAT_HI_EXP - _LAT_LO_EXP) * _LAT_PER_DECADE + 1)
)
_N_BUCKETS = len(LATTICE_EDGES) + 1        # + overflow (> last edge)


def bucket_index(value: float) -> int:
    """The lattice bucket holding ``value``: the first bucket whose
    upper edge is ``>= value`` (Prometheus ``le`` semantics — a value
    exactly on an edge belongs to that edge's bucket), with the final
    index catching overflow.  ``bisect`` on the precomputed edges, so
    boundary membership is exact — no float-log wobble."""
    return bisect_left(LATTICE_EDGES, value)


# ---------------------------------------------------------------------- #
# instruments
# ---------------------------------------------------------------------- #

class Counter:
    """Monotonic total.  Merge = sum."""

    __slots__ = ("value",)

    def __init__(self, value: float = 0.0):
        self.value = float(value)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def to_snapshot(self) -> dict:
        return {"type": "counter", "value": self.value}

    @classmethod
    def from_snapshot(cls, d: dict) -> "Counter":
        return cls(float(d.get("value", 0.0)))

    def merge(self, d: dict) -> None:
        self.value += float(d.get("value", 0.0))


class Gauge:
    """Last-set value + the max it ever held.  Merge keeps the max of
    both (a merged queue-depth gauge answers "how deep did any rank's
    queue get", which is the fleet question)."""

    __slots__ = ("last", "max")

    def __init__(self, last: Optional[float] = None,
                 max: Optional[float] = None):
        self.last = last
        self.max = max

    def set(self, value: float) -> None:
        value = float(value)
        self.last = value
        self.max = value if self.max is None else builtins_max(
            self.max, value)

    def to_snapshot(self) -> dict:
        return {"type": "gauge", "last": self.last, "max": self.max}

    @classmethod
    def from_snapshot(cls, d: dict) -> "Gauge":
        return cls(d.get("last"), d.get("max"))

    def merge(self, d: dict) -> None:
        for attr in ("last", "max"):
            v = d.get(attr)
            if v is None:
                continue
            cur = getattr(self, attr)
            setattr(self, attr,
                    v if cur is None else builtins_max(cur, v))


builtins_max = max      # `Gauge.max` shadows the builtin in its scope


class Histogram:
    """Latency distribution on the shared lattice.

    Exact below the cap: until ``sample_cap`` observations the raw
    samples are retained, and :meth:`percentile` computes the
    numpy-``linear``-identical exact quantile.  Past the cap the
    samples are dropped (memory stays bounded however long the job
    runs) and quantiles interpolate within the lattice bucket the
    target rank lands in, clamped to the observed ``[min, max]``.

    Merge (:meth:`merge`) is bucket-wise sum + count/sum/min/max
    folds; exactness survives a merge whenever the combined sample
    count still fits the cap.

    **Exemplars.**  ``observe(value, exemplar="<trace_id>")`` retains
    ONE exemplar per lattice bucket (newest wins — bounded by the
    bucket count, never by traffic), so a percentile resolves to a
    concrete causal trace: :meth:`exemplar_for` maps the bucket a
    quantile lands in back to the retained ``(trace_id, value, ts)``.
    Exemplars ride snapshots, merges and the Prometheus exposition
    (OpenMetrics ``# {trace_id="..."} value ts`` suffix on ``_bucket``
    rows); observations without an exemplar cost nothing extra.
    """

    SAMPLE_CAP = 512

    __slots__ = ("count", "sum", "min", "max", "_counts", "_samples",
                 "sample_cap", "_exemplars")

    def __init__(self, sample_cap: Optional[int] = None):
        self.sample_cap = (self.SAMPLE_CAP if sample_cap is None
                           else int(sample_cap))
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._counts = [0] * _N_BUCKETS
        self._samples: Optional[List[float]] = []
        # {bucket_index: [exemplar_id, value, wall_ts]} — allocated on
        # the first exemplar-carrying observe, so exemplar-free
        # histograms pay one None check
        self._exemplars: Optional[Dict[int, list]] = None

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        idx = bucket_index(value)
        self._counts[idx] += 1
        if exemplar is not None:
            if self._exemplars is None:
                self._exemplars = {}
            self._exemplars[idx] = [str(exemplar), value, time.time()]
        if self._samples is not None:
            if len(self._samples) < self.sample_cap:
                self._samples.append(value)
            else:
                self._samples = None    # over the cap: buckets only

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    @property
    def exact(self) -> bool:
        """True while every observation is still individually retained
        (percentiles are exact, not interpolated)."""
        return (self._samples is not None
                and len(self._samples) == self.count)

    def percentile(self, q: float) -> Optional[float]:
        """The ``q``-th percentile (``0 <= q <= 100``); ``None`` when
        empty.  Exact (numpy ``linear``) below the cap, interpolated
        bucket quantile above it."""
        if self.count == 0:
            return None
        if self.exact:
            s = sorted(self._samples)
            rank = (q / 100.0) * (len(s) - 1)
            lo = int(math.floor(rank))
            hi = min(lo + 1, len(s) - 1)
            return s[lo] + (s[hi] - s[lo]) * (rank - lo)
        target = (q / 100.0) * self.count
        cum = 0
        for i, c in enumerate(self._counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo_edge = 0.0 if i == 0 else LATTICE_EDGES[i - 1]
                if i < len(LATTICE_EDGES):
                    hi_edge = LATTICE_EDGES[i]
                else:
                    # overflow bucket: the observed max bounds it; a
                    # wire round trip loses min/max, so degrade to the
                    # last edge (a lower bound) rather than crash
                    hi_edge = self.max if self.max is not None \
                        else lo_edge
                est = lo_edge + (hi_edge - lo_edge) * (
                    (target - cum) / c)
                # the observed extrema tighten the bucket's edges
                if self.min is not None:
                    est = builtins_max(est, self.min)
                if self.max is not None:
                    est = min(est, self.max)
                return est
            cum += c
        return self.max

    def bucket_counts(self) -> Dict[int, int]:
        """Sparse ``{bucket_index: count}`` (the merge/export wire
        form; index ``len(LATTICE_EDGES)`` is the overflow bucket)."""
        return {i: c for i, c in enumerate(self._counts) if c}

    def count_above(self, index: int) -> int:
        """Exact count of observations in buckets STRICTLY above
        ``index`` — the burn-rate bad-count read (a latency SLO's
        threshold rounds to a lattice edge, so this is never
        interpolated).  O(buckets) over the raw counts list; the
        alert-evaluation hot path, so no dict is built."""
        return sum(self._counts[index + 1:])

    def exemplars(self) -> Dict[int, tuple]:
        """``{bucket_index: (exemplar_id, value, wall_ts)}`` for every
        bucket holding a retained exemplar."""
        if not self._exemplars:
            return {}
        return {i: tuple(e) for i, e in dict(self._exemplars).items()}

    def exemplar_for(self, q: float) -> Optional[tuple]:
        """The retained exemplar nearest the ``q``-th percentile:
        the bucket that percentile lands in, else the closest bucket
        ABOVE it (a p99 inquiry wants the offending tail request, so
        ties resolve upward), else the closest below.  Returns
        ``(exemplar_id, value, wall_ts)`` or ``None`` when no exemplar
        was ever retained."""
        if not self._exemplars:
            return None
        p = self.percentile(q)
        if p is None:
            return None
        idx = bucket_index(p)
        held = sorted(self._exemplars)
        above = [i for i in held if i >= idx]
        best = above[0] if above else held[-1]
        return tuple(self._exemplars[best])

    def to_snapshot(self) -> dict:
        snap = {
            "type": "histogram",
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "counts": self.bucket_counts(),
            "samples": (list(self._samples)
                        if self._samples is not None else None),
        }
        if self._exemplars:
            # dict() is a single C-level copy under the GIL — a
            # concurrent observe() landing a first exemplar in a new
            # bucket (serving thread vs a statusz scrape) can never
            # surface as dictionary-changed-size mid-iteration
            snap["exemplars"] = {i: list(e)
                                 for i, e
                                 in dict(self._exemplars).items()}
        return snap

    @classmethod
    def from_snapshot(cls, d: dict) -> "Histogram":
        h = cls()
        h.merge(d)
        return h

    def merge(self, d: dict) -> None:
        """Fold a snapshot dict in: bucket-wise sum (the shared lattice
        makes this exact), count/sum adds, min/max folds, samples kept
        only while the combined count still fits the cap."""
        self.count += int(d.get("count", 0))
        self.sum += float(d.get("sum", 0.0))
        for attr, fold in (("min", min), ("max", builtins_max)):
            v = d.get(attr)
            if v is not None:
                cur = getattr(self, attr)
                setattr(self, attr, v if cur is None else fold(cur, v))
        for i, c in (d.get("counts") or {}).items():
            self._counts[int(i)] += int(c)     # str keys post-JSON
        for i, e in (d.get("exemplars") or {}).items():
            idx = int(i)
            if self._exemplars is None:
                self._exemplars = {}
            cur = self._exemplars.get(idx)
            # newest wall timestamp wins per bucket (a None ts — a
            # wire round trip that lost it — loses to any real one);
            # EQUAL timestamps tie-break on the exemplar id so the
            # merged winner is identical whatever order ranks fold in
            ts_new, ts_cur = ((e[2] or 0.0),
                              0.0 if cur is None else (cur[2] or 0.0))
            if cur is None or ts_new > ts_cur or (
                    ts_new == ts_cur and str(e[0]) > str(cur[0])):
                self._exemplars[idx] = [str(e[0]), float(e[1]),
                                        e[2] if e[2] is None
                                        else float(e[2])]
        other = d.get("samples")
        if (self._samples is not None and other is not None
                and len(self._samples) + len(other) <= self.sample_cap):
            self._samples.extend(float(v) for v in other)
        else:
            self._samples = None


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _NullInstrument:
    """The disabled-path instrument: ONE shared instance answering
    every record method as a no-op, so a disabled registry allocates
    nothing per record (pinned by test — the TraceRecorder
    ``_NULL_SPAN`` discipline).  The READ surface answers like an
    empty histogram/counter (count 0, ``percentile``/``mean`` →
    ``None``) so consumers that read live instruments — e.g. a
    service-time predictor over ``registry.histogram("serve/ttft")``
    — degrade to "no data" instead of crashing when the registry is
    disabled."""

    __slots__ = ()

    count = 0
    sum = 0.0
    min = None
    max = None
    value = 0.0
    last = None
    mean = None

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        pass

    def percentile(self, q: float) -> None:
        return None

    def count_above(self, index: int) -> int:
        return 0

    def exemplar_for(self, q: float) -> None:
        return None

    def exemplars(self) -> dict:
        return {}


_NULL_INSTRUMENT = _NullInstrument()


# ---------------------------------------------------------------------- #
# registry
# ---------------------------------------------------------------------- #

class MetricsRegistry:
    """Process-global name → instrument table.

    Disabled (the production default until ``CHAINERMN_TPU_METRICS=1``
    or :meth:`enable`): the instrument getters return the shared
    no-op singleton and the convenience recorders early-return — the
    instrumented hot paths (engine admit/evict, updater step,
    checkpoint save) pay one attribute read and nothing else.

    Instrument names are slash-namespaced like span names
    (``serve/ttft``, ``train/step_time``, ``checkpoint/quarantined``);
    a name keeps its first-registered type for the registry's lifetime
    (re-registering under another type raises — silent shadowing would
    corrupt the merge math).
    """

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def __len__(self) -> int:
        return len(self._metrics)

    def _get(self, name: str, cls):
        if not self.enabled:
            return _NULL_INSTRUMENT
        inst = self._metrics.get(name)
        if inst is None:
            with self._lock:
                inst = self._metrics.get(name)
                if inst is None:
                    inst = cls()
                    self._metrics[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} is a {type(inst).__name__}, not a "
                f"{cls.__name__} — one name, one instrument type")
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    # convenience recorders (what the instrumented call sites use) --- #

    def inc(self, name: str, n: float = 1.0) -> None:
        if not self.enabled:
            return
        self.counter(name).inc(n)

    def set(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        self.gauge(name).set(value)

    def observe(self, name: str, value: float,
                exemplar: Optional[str] = None) -> None:
        if not self.enabled:
            return
        self.histogram(name).observe(value, exemplar=exemplar)

    # snapshot / lifecycle ------------------------------------------- #

    def snapshot(self, prefix: Optional[str] = None) -> Dict[str, dict]:
        """Name → snapshot-dict (JSON-safe, detached from the live
        instruments), optionally restricted to a name prefix."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: inst.to_snapshot() for name, inst in items
                if prefix is None or name.startswith(prefix)}

    def digest(self) -> Dict[str, Optional[float]]:
        """Counter values and gauge lasts only — the cheap live read
        a status page wants per scrape (a full :meth:`snapshot` would
        also serialize every histogram's retained samples and
        exemplars just to be discarded)."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Optional[float]] = {}
        for name, inst in items:
            if isinstance(inst, Counter):
                out[name] = inst.value
            elif isinstance(inst, Gauge):
                out[name] = inst.last
        return out

    def load(self, snapshot: Dict[str, dict]) -> None:
        """Fold a snapshot into this registry (merge semantics per
        instrument type) — the inverse of :meth:`snapshot` and the
        worker half of :func:`merge_metrics`."""
        for name in sorted(snapshot):
            d = snapshot[name]
            cls = _TYPES.get(d.get("type"))
            if cls is None:
                continue
            inst = self._metrics.get(name)
            if inst is None:
                with self._lock:
                    inst = self._metrics.setdefault(name, cls())
            if isinstance(inst, cls):   # divergent-type rows are dropped
                inst.merge(d)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


def _from_env() -> MetricsRegistry:
    enabled = os.environ.get("CHAINERMN_TPU_METRICS", "") \
        not in ("", "0")
    return MetricsRegistry(enabled=enabled)


_GLOBAL = _from_env()


def get_registry() -> MetricsRegistry:
    """The process-global registry every instrumented subsystem records
    into (disabled by default — see module docstring)."""
    return _GLOBAL


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the global registry (tests, scoped benches); returns the
    previous one so callers can restore it."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = registry
    return prev


# ---------------------------------------------------------------------- #
# cross-rank merge
# ---------------------------------------------------------------------- #

def merge_metrics(comm, registry: Optional[MetricsRegistry] = None
                  ) -> MetricsRegistry:
    """Allgather every process's snapshot and fold them into ONE merged
    registry — counters sum, gauges keep max-of-{last,max}, histograms
    bucket-wise sum on the shared lattice, divergent name sets union
    (ranks may run different extensions — each metric merges over the
    ranks that reported it, the ``ObservationAggregator`` convention).

    COLLECTIVE: every process must call.  ``allgather_obj`` hands every
    rank the same rank-ordered rows and the fold is deterministic, so
    the merged snapshot is identical on every rank — safe to gate
    rank-0-only exposition on.
    """
    reg = registry if registry is not None else get_registry()
    rows = comm.allgather_obj(reg.snapshot())
    merged = MetricsRegistry(enabled=True)
    for row in rows:
        merged.load(row)
    return merged


# ---------------------------------------------------------------------- #
# exposition: Prometheus text + JSONL
# ---------------------------------------------------------------------- #

def _prom_name(name: str) -> str:
    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return "_" + n if n and n[0].isdigit() else n


def _prom_labels(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _prom_float(v: float) -> str:
    return format(float(v), ".17g")     # round-trips doubles exactly


def to_prometheus(snapshot, labels: Optional[Dict[str, str]] = None,
                  openmetrics: bool = False) -> str:
    """Render a registry (or a :meth:`MetricsRegistry.snapshot` dict)
    as Prometheus exposition text, node-exporter-textfile style.

    ``openmetrics=True`` emits the OpenMetrics dialect: exemplar
    suffixes on bucket rows that hold one, and counter samples under
    the mandatory ``_total`` name (a strict OM parser — Prometheus's
    own when the scrape negotiated openmetrics — rejects both missing
    ``_total`` and, in the classic dialect, the exemplar grammar).
    The default stays classic ``text/plain; version=0.0.4`` with
    neither (every pre-exemplar caller keeps emitting parseable
    0.0.4: :func:`export_prometheus` / ``MetricsTextfile`` / watchdog
    stall reports); the negotiating pull surface (``/metricsz``) opts
    in per scrape, and :func:`parse_prometheus_text` accepts both
    dialects.

    Histograms emit cumulative ``_bucket{le=...}`` rows for every
    NON-EMPTY lattice bucket plus the mandatory ``le="+Inf"``, and
    ``_sum`` / ``_count`` — successive-row differences reconstruct the
    exact bucket counts (:func:`histogram_from_prometheus`), and the
    17-digit ``le`` values match the lattice edges float-exactly.
    ``labels`` (e.g. ``{"rank": "0"}``) ride every sample.
    """
    if isinstance(snapshot, MetricsRegistry):
        snapshot = snapshot.snapshot()
    lines: List[str] = []
    lab = _prom_labels(labels)
    for name in sorted(snapshot):
        d = snapshot[name]
        pname = _prom_name(name)
        kind = d.get("type")
        if kind == "counter":
            lines.append(f"# TYPE {pname} counter")
            sample = f"{pname}_total" if openmetrics else pname
            lines.append(f"{sample}{lab} {_prom_float(d['value'])}")
        elif kind == "gauge":
            if d.get("last") is None:
                continue
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname}{lab} {_prom_float(d['last'])}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            counts = {int(i): int(c)
                      for i, c in (d.get("counts") or {}).items()}
            exes = ({} if not openmetrics else
                    {int(i): e
                     for i, e in (d.get("exemplars") or {}).items()})
            cum = 0
            for i in sorted(counts):
                cum += counts[i]
                le = ("+Inf" if i >= len(LATTICE_EDGES)
                      else _prom_float(LATTICE_EDGES[i]))
                blab = _prom_labels(dict(labels or {}, le=le))
                row = f"{pname}_bucket{blab} {cum}"
                ex = exes.get(i)
                if ex is not None:
                    # OpenMetrics exemplar syntax: the bucket row links
                    # straight to the causal trace of one observation
                    # that landed in it.  Caller-propagated trace ids
                    # are arbitrary strings — sanitize to the label
                    # charset so a quote/brace can never corrupt the
                    # exposition (or defeat the parser's round-trip)
                    exid = re.sub(r"[^A-Za-z0-9_.:\-]", "_",
                                  str(ex[0]))
                    row += (f' # {{trace_id="{exid}"}} '
                            f"{_prom_float(ex[1])}")
                    if ex[2] is not None:
                        row += f" {_prom_float(ex[2])}"
                lines.append(row)
            if not counts or max(counts) < len(LATTICE_EDGES):
                blab = _prom_labels(dict(labels or {}, le="+Inf"))
                lines.append(f"{pname}_bucket{blab} {cum}")
            lines.append(f"{pname}_sum{lab} {_prom_float(d['sum'])}")
            lines.append(f"{pname}_count{lab} {int(d['count'])}")
    if openmetrics:
        # the mandatory document terminator — a strict OM parser
        # rejects an exposition without it as truncated
        lines.append("# EOF")
    return "\n".join(lines) + ("\n" if lines else "")


_PROM_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)"
    # optional OpenMetrics exemplar suffix: # {labels} value [ts]
    r"(?:\s+#\s+\{(?P<exlabels>[^}]*)\}\s+(?P<exvalue>\S+)"
    r"(?:\s+(?P<exts>\S+))?)?$")


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Parse :func:`to_prometheus` output back into snapshot-shaped
    dicts: ``{name: {"type", "value"|"last"|("count","sum","buckets")}}``
    where histogram ``buckets`` is ``[(le, cumulative_count), ...]`` in
    emission order (``le`` is ``math.inf`` for ``+Inf``) and
    ``exemplars`` (when present) maps ``le`` to
    ``[trace_id, value, ts]`` parsed from the OpenMetrics exemplar
    suffix.  Pre-exemplar text parses identically to before — the
    suffix is optional in both the grammar and the output (the
    back-compat half the tests pin, both directions)."""
    types: Dict[str, str] = {}
    out: Dict[str, dict] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        name, labels, value = (m.group("name"), m.group("labels") or "",
                               m.group("value"))
        base, suffix = name, None
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and types.get(name[: -len(suf)]) \
                    == "histogram":
                base, suffix = name[: -len(suf)], suf
                break
        # the OpenMetrics dialect samples counters under _total
        if suffix is None and name.endswith("_total") \
                and types.get(name[: -len("_total")]) == "counter":
            base = name[: -len("_total")]
        kind = types.get(base)
        if kind == "histogram":
            entry = out.setdefault(base, {"type": "histogram",
                                          "buckets": [], "count": 0,
                                          "sum": 0.0})
            if suffix == "_bucket":
                le_m = re.search(r'le="([^"]+)"', labels)
                if le_m:
                    le = (math.inf if le_m.group(1) == "+Inf"
                          else float(le_m.group(1)))
                    entry["buckets"].append((le, int(float(value))))
                    if m.group("exvalue") is not None:
                        ex_id = re.search(r'trace_id="([^"]*)"',
                                          m.group("exlabels") or "")
                        ts = m.group("exts")
                        entry.setdefault("exemplars", {})[le] = [
                            ex_id.group(1) if ex_id else "",
                            float(m.group("exvalue")),
                            float(ts) if ts is not None else None]
            elif suffix == "_sum":
                entry["sum"] = float(value)
            elif suffix == "_count":
                entry["count"] = int(float(value))
        elif kind == "counter":
            out[base] = {"type": "counter", "value": float(value)}
        elif kind == "gauge":
            out[base] = {"type": "gauge", "last": float(value)}
    return out


def histogram_from_prometheus(entry: dict) -> Histogram:
    """Rebuild a lattice :class:`Histogram` from a parsed exposition
    entry.  Bucket counts are exact (cumulative differences mapped back
    to lattice indices by float-equal ``le`` match); raw samples and
    min/max do not survive the wire, so percentiles come from the
    interpolated-bucket path.  Exemplars round-trip onto their lattice
    buckets (the exemplar→trace link survives exposition)."""
    h = Histogram()
    h._samples = None
    h.count = int(entry.get("count", 0))
    h.sum = float(entry.get("sum", 0.0))
    exemplars = entry.get("exemplars") or {}

    def lattice_idx(le: float) -> int:
        if math.isinf(le):
            return len(LATTICE_EDGES)
        idx = bisect_left(LATTICE_EDGES, le)
        if idx >= len(LATTICE_EDGES) or LATTICE_EDGES[idx] != le:
            raise ValueError(
                f"le={le!r} is not a lattice edge — was this text "
                "produced by a different lattice version?")
        return idx

    prev = 0
    for le, cum in entry.get("buckets", []):
        c = cum - prev
        prev = cum
        if c <= 0:
            continue
        h._counts[lattice_idx(le)] += c
    for le, ex in exemplars.items():
        if h._exemplars is None:
            h._exemplars = {}
        h._exemplars[lattice_idx(le)] = [str(ex[0]), float(ex[1]),
                                         None if ex[2] is None
                                         else float(ex[2])]
    return h


def export_prometheus(path: str, registry=None,
                      labels: Optional[Dict[str, str]] = None,
                      openmetrics: bool = False) -> str:
    """Write the exposition text atomically (tmp + rename — the
    node-exporter textfile-collector contract: a scraper must never
    read a half-written file).  The OpenMetrics dialect (exemplars,
    ``_total`` counters) defaults OFF here: the textfile collector
    speaks classic 0.0.4, whose parsers reject the OM grammar —
    turning request tracing on must never break an existing scrape."""
    reg = registry if registry is not None else get_registry()
    text = to_prometheus(reg, labels=labels, openmetrics=openmetrics)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


def append_jsonl(path: str, entry: dict) -> str:
    """Append ``entry`` as ONE JSON line, crash-atomically: the line is
    fully serialized first and lands via a single ``O_APPEND`` write
    syscall, so a SIGKILL (or a concurrent appender) can never leave a
    TORN last line — a reader sees the line entirely or not at all.
    The JSONL sibling of :func:`export_prometheus`'s tmp+rename
    contract; every ``*.jsonl`` report in the stack (metrics/straggler/
    goodput/alert logs) flushes through here."""
    data = (json.dumps(entry, default=float) + "\n").encode()
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    try:
        view = memoryview(data)
        while view:
            # a short write (ENOSPC mid-line, signal) would be exactly
            # the torn tail this function promises away — finish or
            # raise, never return with bytes unwritten
            view = view[os.write(fd, view):]
    finally:
        os.close(fd)
    return path


def export_jsonl(path: str, registry=None, **extra) -> str:
    """Append ONE JSON line ``{"ts", ..., "metrics": snapshot}`` — the
    time-series form (each flush is a point; dashboards diff
    counters/buckets between lines).  Atomic per line
    (:func:`append_jsonl`)."""
    reg = registry if registry is not None else get_registry()
    entry = {"ts": time.time(), **extra, "metrics": reg.snapshot()}
    return append_jsonl(path, entry)


# ---------------------------------------------------------------------- #
# trainer extensions
# ---------------------------------------------------------------------- #

class GoodputReport:
    """Goodput/badput accounting: decompose each report window's wall
    time into productive compute vs named badput, from the flight
    recorder's phase stats.

    On each trigger, the wall clock since the last fire is the window;
    the recorder's per-phase totals are drained from this report's OWN
    phase channel (``open_phase_channel`` — an independent accumulator,
    so a ``StragglerReport`` draining the default channel on any
    trigger still sees every interval) and decomposed into:

    - ``productive_s`` — ``step/dispatch`` + ``step/accum_window`` +
      ``step/retire``: dispatching windows and blocking on device
      results, i.e. wall time the accelerator is doing model work.
    - ``host_blocked_s`` — ``step/host``: waiting for input assembly
      (the prefetch residual).
    - ``checkpoint_s`` — ``checkpoint/save_shard`` +
      ``checkpoint/resume`` (the outermost checkpoint spans; an
      async-write checkpointer only bills its main-thread half here —
      the overlapped disk write is not badput).
    - ``exchange_probe_s`` — ``step/exchange_probe``: the isolated
      drift-guard re-times.
    - ``compile_s`` — XLA compiles, fed from the PROGRAM LEDGER
      (:mod:`chainermn_tpu.utils.programs`): the window's delta of
      ``ledger.compile_seconds(COMPILE_SCOPES)`` — the ``train/``
      labels, whose compiles happen INSIDE the dispatch spans (the
      first call of a new program shape traces+compiles under
      ``step/dispatch``), so the delta is subtracted out of
      ``productive_s`` (clamped at 0) — a post-resize recompile or an
      epoch-tail shape shows up as compile badput instead of hiding
      inside productive time.  Autotune-probe compiles stay in
      ``exchange_probe_s``, eval compiles in ``stall_s``, and a
      colocated serving engine's compiles bill nothing here.  Zero
      whenever the ledger is disabled.
    - ``stall_s`` — the unaccounted remainder (extensions, evaluators,
      GC pauses, genuine stalls).

    ``goodput = productive_s / window_s`` is observed as
    ``main/goodput`` and mirrored into the metrics registry (gauge
    ``train/goodput``; per-category ``goodput/*_s`` counters accumulate
    the decomposition for scrapers).  The full report lands in
    :attr:`last_report` and (``write=True``) ``<out>/goodput.jsonl``.

    Needs the flight recorder ENABLED — with it off every phase drains
    empty and the whole window would read as stall, so the report marks
    itself ``trace_enabled: False`` and observes nothing.
    """

    trigger = (1, "epoch")
    priority = 87   # near StragglerReport (85); order is immaterial —
    # each drains its own phase channel

    CHANNEL = "goodput"

    PRODUCTIVE = ("step/dispatch", "step/accum_window", "step/retire")
    HOST_BLOCKED = ("step/host",)
    CHECKPOINT = ("checkpoint/save_shard", "checkpoint/resume")
    EXCHANGE_PROBE = ("step/exchange_probe",)

    def __init__(self, comm=None, recorder=None, write: bool = True,
                 registry: Optional[MetricsRegistry] = None):
        self.comm = comm
        self.recorder = recorder
        self.write = write
        self.registry = registry
        self.last_report: Optional[dict] = None
        self._t_last: Optional[float] = None
        self._compile_mark: Optional[float] = None

    def _recorder(self):
        rec = self.recorder
        if rec is None:
            from chainermn_tpu.utils.telemetry import get_recorder

            rec = get_recorder()
        # idempotent: the private channel must exist before (and keep
        # existing while) spans accumulate — re-asserted per access so
        # a swapped global recorder picks it up from the next span on.
        # The name filter keeps the channel from accumulating (and
        # retaining) spans this report never drains.
        rec.open_phase_channel(
            self.CHANNEL,
            names=(self.PRODUCTIVE + self.HOST_BLOCKED
                   + self.CHECKPOINT + self.EXCHANGE_PROBE))
        return rec

    #: Ledger label scopes whose compiles bill into THIS report's
    #: compile badput.  ``train/`` ONLY: those are the compiles that
    #: happen inside the dispatch spans (so subtracting them out of
    #: ``productive_s`` is exact).  ``autotune/`` compiles bill inside
    #: the ``step/exchange_probe`` span (already ``exchange_probe_s``
    #: — counting them here would double-bill), ``eval/`` compiles
    #: inside evaluator extension time (``stall_s``), and a colocated
    #: serving engine's ``serve/``/``spec/`` compiles must never
    #: depress a training window at all.
    COMPILE_SCOPES = ("train/",)

    def _compile_delta(self) -> float:
        """Seconds of XLA compile the program ledger recorded since
        the last window, training-side labels only (0.0 with the
        ledger disabled or absent)."""
        from chainermn_tpu.utils.programs import get_ledger

        total = get_ledger().compile_seconds(self.COMPILE_SCOPES)
        if self._compile_mark is None or total < self._compile_mark:
            # first window, or the ledger was cleared/swapped mid-run:
            # no baseline to difference against
            self._compile_mark = total
            return 0.0
        delta = total - self._compile_mark
        self._compile_mark = total
        return delta

    def initialize(self, trainer=None) -> None:
        self._recorder()        # open the channel before the first window
        self._t_last = time.perf_counter()
        self._compile_delta()   # anchor the ledger baseline

    def __call__(self, trainer=None) -> None:
        rec = self._recorder()
        now = time.perf_counter()
        if self._t_last is None:        # used without initialize()
            self._t_last = now
        window = now - self._t_last
        self._t_last = now
        names = (self.PRODUCTIVE + self.HOST_BLOCKED + self.CHECKPOINT
                 + self.EXCHANGE_PROBE)
        drained = rec.drain_phase_stats(names, channel=self.CHANNEL)

        def total(group: Sequence[str]) -> float:
            return sum(drained[n]["total_s"] for n in group
                       if n in drained)

        productive = total(self.PRODUCTIVE)
        host_blocked = total(self.HOST_BLOCKED)
        checkpoint = total(self.CHECKPOINT)
        probe = total(self.EXCHANGE_PROBE)
        compile_s = self._compile_delta()
        # compiles bill inside the dispatch spans (see class
        # docstring): move them out of productive, clamped — a compile
        # landing outside any span (engine warmup between windows)
        # would otherwise drive productive negative
        productive = builtins_max(0.0, productive - compile_s)
        accounted = (productive + host_blocked + checkpoint + probe
                     + compile_s)
        stall = builtins_max(0.0, window - accounted)
        goodput = (productive / window
                   if window > 0 and rec.enabled else None)
        self.last_report = {
            "iteration": (trainer.updater.iteration
                          if trainer is not None else None),
            "window_s": window,
            "productive_s": productive,
            "badput": {
                "host_blocked_s": host_blocked,
                "checkpoint_s": checkpoint,
                "exchange_probe_s": probe,
                "compile_s": compile_s,
                "stall_s": stall,
            },
            "goodput": goodput,
            "trace_enabled": rec.enabled,
        }
        if goodput is not None:
            if trainer is not None:
                trainer.observation["main/goodput"] = goodput
            reg = (self.registry if self.registry is not None
                   else get_registry())
            reg.set("train/goodput", goodput)
            reg.inc("goodput/productive_s", productive)
            reg.inc("goodput/host_blocked_s", host_blocked)
            reg.inc("goodput/checkpoint_s", checkpoint)
            reg.inc("goodput/exchange_probe_s", probe)
            reg.inc("goodput/compile_s", compile_s)
            reg.inc("goodput/stall_s", stall)
        if (self.write and trainer is not None
                and (self.comm is None
                     or getattr(self.comm, "inter_rank", 0) == 0)):
            try:
                path = os.path.join(getattr(trainer, "out", "."),
                                    "goodput.jsonl")
                append_jsonl(path, self.last_report)
            except OSError:
                pass            # observability must never kill training


class MetricsTextfile:
    """Trainer extension flushing the registry to a Prometheus textfile
    on trigger (node-exporter textfile-collector convention: atomic
    tmp+rename writes of ``<out>/metrics.prom``).

    With ``comm=`` on a multi-process world the flush is COLLECTIVE:
    every rank enters :func:`merge_metrics` and rank 0 writes the one
    merged file (samples labeled ``rank="merged"``).  Without a comm
    (or single-process) each process writes its own file, rank-labeled.
    """

    trigger = (1, "epoch")
    priority = 40   # after GoodputReport (87) / StragglerReport (85)
    # fed the registry, before LogReport-style consumers don't matter

    def __init__(self, comm=None, filename: str = "metrics.prom",
                 path: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.comm = comm
        self.filename = filename
        self.path = path
        self.registry = registry

    def initialize(self, trainer) -> None:
        if self.path is None:
            self.path = os.path.join(getattr(trainer, "out", "."),
                                     self.filename)

    def __call__(self, trainer=None) -> None:
        if self.path is None:
            self.path = self.filename
        reg = (self.registry if self.registry is not None
               else get_registry())
        if self.comm is not None \
                and getattr(self.comm, "inter_size", 1) > 1:
            merged = merge_metrics(self.comm, reg)
            if self.comm.inter_rank != 0:
                return
            reg, labels = merged, {"rank": "merged"}
        else:
            rank = getattr(self.comm, "inter_rank", 0) \
                if self.comm is not None else 0
            labels = {"rank": str(rank)}
        try:
            export_prometheus(self.path, reg, labels=labels)
        except OSError:
            pass                # a full disk must never kill training
