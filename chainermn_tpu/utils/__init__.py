"""Utility subsystems: serialization, profiling/tracing, logging."""

from chainermn_tpu.utils.serialization import load_state, save_state

__all__ = ["load_state", "save_state"]
