"""Utility subsystems: serialization, profiling/tracing, logging."""

from chainermn_tpu.utils.profiling import (
    Profiler,
    ProfileReport,
    get_profiler,
    profiled_communicator,
    trace,
)
from chainermn_tpu.utils.serialization import load_state, save_state

__all__ = [
    "ProfileReport",
    "Profiler",
    "get_profiler",
    "load_state",
    "profiled_communicator",
    "save_state",
    "trace",
]
