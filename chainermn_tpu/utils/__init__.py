"""Utility subsystems: serialization, profiling/tracing, the flight
recorder (telemetry), comm modelling, and the measured exchange-plan
autotuner."""

from chainermn_tpu.utils.autotune import (
    Plan,
    PlanCell,
    autotune_plan,
    default_cache_path,
    load_cached_plan,
    store_plan,
)
from chainermn_tpu.utils.comm_model import (
    CollectiveStats,
    LinkParams,
    assert_accum_collectives,
    assert_overlap_collectives,
    axis_collective_report,
    choose_accum_steps,
    choose_bucket_bytes,
    choose_prefetch_depth,
    collective_stats,
    overlap_exposed_time,
    stablehlo_collective_stats,
    wire_bytes_per_device,
)
from chainermn_tpu.utils.profiling import (
    Profiler,
    ProfileReport,
    get_profiler,
    profiled_communicator,
    trace,
)
from chainermn_tpu.utils.serialization import (
    SnapshotCorruptError,
    load_state,
    save_state,
    verify_state,
)
from chainermn_tpu.utils.telemetry import (
    MetricsExport,
    StragglerReport,
    TraceRecorder,
    get_recorder,
    merge_traces,
    set_recorder,
)

__all__ = [
    "MetricsExport",
    "StragglerReport",
    "TraceRecorder",
    "get_recorder",
    "merge_traces",
    "set_recorder",
    "CollectiveStats",
    "LinkParams",
    "Plan",
    "PlanCell",
    "ProfileReport",
    "Profiler",
    "SnapshotCorruptError",
    "assert_accum_collectives",
    "assert_overlap_collectives",
    "autotune_plan",
    "axis_collective_report",
    "overlap_exposed_time",
    "default_cache_path",
    "load_cached_plan",
    "store_plan",
    "choose_accum_steps",
    "choose_bucket_bytes",
    "choose_prefetch_depth",
    "collective_stats",
    "get_profiler",
    "load_state",
    "profiled_communicator",
    "save_state",
    "stablehlo_collective_stats",
    "trace",
    "verify_state",
    "wire_bytes_per_device",
]
