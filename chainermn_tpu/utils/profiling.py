"""Profiling/tracing subsystem — a first-class facility the reference
never had (SURVEY §5: its practice was external ``nvprof``/MPI tracing;
the only instrumentation surface was pure_nccl's CUDA stream usage).

Three layers:

- :class:`Profiler` — named duration/counter registry with
  ``time_block(name)`` context timing and a stats table.  Durations are
  *host-observed* (dispatch → value materialisation), which is what the
  user can act on under async dispatch.
- :func:`profiled_communicator` — wraps any communicator so every eager
  collective (``allreduce``, ``bcast_obj``, ...) is timed into a
  profiler, with payload byte counts — the per-collective duration
  metrics SURVEY §5 prescribes.
- :func:`trace` — delegates to ``jax.profiler`` for full XLA/TPU traces
  viewable in TensorBoard/XProf (device-side truth; the Profiler is the
  cheap always-on layer).

Plus :class:`ProfileReport`, a trainer extension printing the table on a
trigger (rank-0 convention, like the reference's LogReport usage).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import jax
import numpy as np

__all__ = [
    "Profiler",
    "ProfileReport",
    "get_profiler",
    "profiled_communicator",
    "trace",
]


@dataclass
class _Stat:
    count: int = 0
    total: float = 0.0
    maximum: float = 0.0
    bytes: int = 0

    def add(self, seconds: float, nbytes: int = 0) -> None:
        self.count += 1
        self.total += seconds
        self.maximum = max(self.maximum, seconds)
        self.bytes += nbytes


@dataclass
class Profiler:
    """Named timing registry.  Thread-compatible (single-writer per name)."""

    stats: Dict[str, _Stat] = field(default_factory=dict)
    enabled: bool = True

    def record(self, name: str, seconds: float, nbytes: int = 0) -> None:
        if not self.enabled:
            return
        self.stats.setdefault(name, _Stat()).add(seconds, nbytes)

    @contextlib.contextmanager
    def time_block(self, name: str, nbytes: int = 0, sync=None):
        """Time a block.  ``sync`` (optional callable or array) is invoked /
        materialised before the clock stops, so async-dispatched device
        work is actually included (block_until_ready alone can return
        early on experimental backends — anchor on a host transfer).

        Disabled → truly zero-cost: no clock reads, and crucially no
        ``device_get`` materialisation — a disabled profiler must never
        collapse the async-dispatch overlap it exists to measure."""
        if not self.enabled:
            yield {}
            return
        t0 = time.perf_counter()
        box = {}
        try:
            yield box
        finally:
            out = box.get("out", sync)
            if callable(out):
                out()
            elif out is not None:
                _materialise(out)
            self.record(name, time.perf_counter() - t0, nbytes)

    def summary(self) -> str:
        if not self.stats:
            return "(no profile data)"
        rows = [("name", "count", "total_s", "mean_ms", "max_ms", "MB")]
        for name in sorted(self.stats):
            s = self.stats[name]
            rows.append((
                name, str(s.count), f"{s.total:.3f}",
                f"{1e3 * s.total / max(s.count, 1):.2f}",
                f"{1e3 * s.maximum:.2f}",
                f"{s.bytes / 1e6:.1f}"))
        widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
        return "\n".join(
            "  ".join(c.ljust(w) for c, w in zip(r, widths)) for r in rows)

    def reset(self) -> None:
        self.stats.clear()


_GLOBAL = Profiler()


def get_profiler() -> Profiler:
    """The default process-global profiler."""
    return _GLOBAL


def _nbytes(x) -> int:
    try:
        return int(jax.tree.reduce(
            lambda a, b: a + b,
            jax.tree.map(lambda v: v.size * v.dtype.itemsize
                         if hasattr(v, "dtype") else 0, x), 0))
    except Exception:
        return 0


def _materialise(out) -> None:
    """Force async-dispatched results to the host (the sync anchor
    ``time_block``'s finally performs) — used when only the flight
    recorder is timing, so its span still covers real completion."""
    jax.tree.map(
        lambda a: np.asarray(jax.device_get(a))
        if hasattr(a, "dtype") else a, out)


_COLLECTIVES = (
    "bcast", "allreduce", "allgather", "alltoall", "gather", "scatter",
    "reduce_scatter", "send", "bcast_obj", "allgather_obj", "gather_obj",
    "allreduce_obj", "scatter_obj", "send_obj", "recv_obj", "barrier",
    "bcast_data", "multi_node_mean_grad",
)


class _ProfiledCommunicator:
    """Transparent proxy timing every eager collective into a profiler.

    Host-observed wall time per call: dispatch, any XLA execution it
    forces, and result materialisation (obj collectives are host-blocking
    already; array collectives are materialised to close the async gap).
    The jitted in-step collectives (``ops.*`` inside shard_map) are NOT
    routed here — those belong to XLA's domain; use :func:`trace` to see
    them.  This matches what the reference could observe per NCCL call.

    Every timed call is also recorded as a ``cat="comm"`` span into the
    flight recorder (:mod:`chainermn_tpu.utils.telemetry`), so eager
    collectives land on the same timeline as the step phases.
    """

    def __init__(self, comm, profiler: Optional[Profiler] = None,
                 prefix: str = "comm."):
        self._comm = comm
        self._profiler = profiler or get_profiler()
        self._prefix = prefix

    def __getattr__(self, name):
        attr = getattr(self._comm, name)
        if name not in _COLLECTIVES or not callable(attr):
            return attr
        profiler, label = self._profiler, self._prefix + name
        from chainermn_tpu.utils.telemetry import get_recorder

        def timed(*args, **kwargs):
            recorder = get_recorder()
            if not profiler.enabled and not recorder.enabled:
                return attr(*args, **kwargs)   # zero accounting overhead
            nbytes = _nbytes(args)
            # recorder span OUTER: time_block materialises the output in
            # its finally, so the inner exit must be the profiler's for
            # both timers to cover the same (synced) interval
            with recorder.span(label, cat="comm", nbytes=nbytes), \
                    profiler.time_block(label, nbytes=nbytes) as box:
                out = attr(*args, **kwargs)
                box["out"] = out
                if not profiler.enabled:
                    # the disabled time_block skips its sync anchor; the
                    # recorder span must still cover real completion
                    _materialise(out)
            return out

        # cache the wrapper on the instance: __getattr__ only fires for
        # missing attributes, so every later access skips the closure
        # rebuild (enabled-ness is re-checked inside per call)
        self.__dict__[name] = timed
        return timed

    @property
    def profiler(self) -> Profiler:
        return self._profiler

    def __repr__(self) -> str:
        return f"ProfiledCommunicator({self._comm!r})"


def profiled_communicator(comm, profiler: Optional[Profiler] = None):
    """Wrap ``comm`` so every collective is timed (see module docstring)."""
    return _ProfiledCommunicator(comm, profiler)


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2):
    """Full device trace via ``jax.profiler`` (TensorBoard/XProf format).

    The device-side complement to :class:`Profiler`: shows per-HLO and
    per-collective device time, fusion decisions, and ICI traffic on real
    TPUs.  Usage::

        with profiling.trace("/tmp/trace"):
            train_some_steps()
    """
    if hasattr(jax.profiler, "ProfileOptions"):
        opts = jax.profiler.ProfileOptions()
        opts.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=opts)
    else:  # older jax: no per-trace options; default tracer levels
        jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfileReport:
    """Trainer extension: print (rank 0) and reset the profiler table.

    With ``comm`` given on a MULTI-process job, the table is aggregated
    across processes first — count/total/bytes summed, max-of-max — via
    ``allgather_obj``, so the printed stats reflect the WORLD, not rank
    0's local view (processes may hold divergent name sets —
    rank-0-only extensions — each name aggregates over the ranks that
    reported it, the ObservationAggregator convention).  The allgather
    is COLLECTIVE: every process must extend the trainer with this
    report on the same trigger (the ObservationAggregator deployment
    shape).  A report registered on rank 0 only must pass
    ``aggregate=False`` to keep the old local-table-with-rank-0-print
    behaviour; single-process worlds skip the collective entirely
    either way.
    """

    trigger = (1, "epoch")
    priority = 60

    def __init__(self, profiler: Optional[Profiler] = None, comm=None,
                 reset: bool = True, aggregate: bool = True):
        self.profiler = profiler or get_profiler()
        self.comm = comm
        self.reset = reset
        self.aggregate = aggregate

    def _aggregate(self) -> Profiler:
        """World-wide stats table (or the local one without a comm /
        on a single process / with ``aggregate=False``)."""
        if self.comm is None or not self.aggregate or \
                getattr(self.comm, "inter_size", 1) <= 1:
            return self.profiler
        gathered = self.comm.allgather_obj({
            name: (s.count, s.total, s.maximum, s.bytes)
            for name, s in self.profiler.stats.items()})
        agg = Profiler()
        for d in gathered:
            for name, (count, total, maximum, nbytes) in d.items():
                st = agg.stats.setdefault(name, _Stat())
                st.count += count
                st.total += total
                st.maximum = max(st.maximum, maximum)
                st.bytes += nbytes
        return agg

    def __call__(self, trainer) -> None:
        table = self._aggregate()
        if self.comm is None or self.comm.rank == 0:
            world = "" if self.comm is None else \
                f", {getattr(self.comm, 'inter_size', 1)} process(es)"
            print(f"[profile @ iter {trainer.updater.iteration}{world}]")
            print(table.summary())
        if self.reset:
            self.profiler.reset()
