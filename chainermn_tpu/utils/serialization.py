"""Pytree snapshot serialization — the package's own serializer (the
reference leaned on ``chainer.serializers.save_npz``; SURVEY §7 step 4 calls
for an orbax-style layout but our own implementation, no orbax dependency).

Format: one ``.npz`` per snapshot holding every leaf as a named array
(``leaf_00000``, ...) plus the pickled treedef — self-contained, atomic
(write to ``.tmp`` then rename), resumable within the same code version.
Device arrays are pulled to host with ``jax.device_get`` so saving works
for sharded/replicated params alike (each process saves its addressable
view — the per-process *shard* file of the multi-node checkpointer).

Integrity: every payload (each leaf's raw bytes and the meta record
itself) carries a CRC32 recorded inside ``__meta__``, so a torn write
the atomic rename could not prevent (disk-full, power cut mid-fsync) or
silent bit rot is DETECTED at load instead of surfacing as an opaque
npz/pickle error deep inside resume.  :func:`verify_state` probes a file
without unpickling leaf data into a tree; :func:`load_state` checks the
same CRCs on its real read path.  Corruption raises the typed
:class:`SnapshotCorruptError` — the checkpointer's fallback-resume path
catches exactly that (docs/RESILIENCE.md).

Shard-only save sets (docs/RESILIENCE.md "Scale-free snapshots"): the
full-state-per-rank layout ``_host_view`` documents costs N× disk on an
N-process world.  A shard-only set instead splits one logical snapshot
into per-mesh-member PART files: part ``m`` holds member ``m``'s rows of
every world-stacked ZeRO-1 "shard" leaf (identified by the topology
signature's per-leaf layout — exactly the metadata
``training/elastic.relayout_state`` already consumes), and the ROOT part
(member 0's) additionally holds every replicated entry (params,
train_state, stack/rep optimizer leaves) ONCE.  Aggregate set cost is
therefore ~1× the state regardless of world size.  The primitives here
are pure and format-level: :func:`build_shard_part` slices one part,
:func:`assemble_shard_state` rebuilds the full state from a COVERING
set (every member present exactly once, verified), and the part record
rides the same CRC-guarded ``__meta__`` as the topology stamp
(:func:`load_state_with_stamps` / :func:`read_shard_part`).  The
checkpointer owns set naming, agreement, quarantine and GC.
"""

from __future__ import annotations

import os
import pickle
import zlib

import jax
import numpy as np

__all__ = ["SHARD_PART_FORMAT", "ShardSetError", "SnapshotCorruptError",
           "assemble_shard_state", "build_shard_part",
           "fsdp_leaf_entries", "load_state",
           "load_state_with_stamps", "load_state_with_topology",
           "read_shard_part", "read_topology", "save_state",
           "verify_state"]


class SnapshotCorruptError(RuntimeError):
    """A snapshot file failed its integrity check (bad CRC, missing
    leaf, undecodable meta, truncated archive).  Typed so recovery code
    (``MultiNodeCheckpointer.maybe_load`` fallback) can distinguish
    "this file is damaged" from programming errors."""


class ShardSetError(RuntimeError):
    """A collection of shard-only part files does not form a valid
    covering set (missing/duplicate members, mismatched worlds or leaf
    indices, no root part).  Typed so the checkpointer's fallback path
    treats it like corruption — skip the set, try the next — instead of
    crashing resume on a half-written set."""


#: Version of the ``shard_part`` meta record.  A reader that does not
#: recognise the version must refuse the part (conservative, like the
#: topology format).  v2 (PR 20) adds dim-sharded ZeRO-3/FSDP leaves
#: (``fsdp_opt_leaves``/``fsdp_param_leaves`` record entries); the
#: reader still accepts v1 sets (``_SHARD_PART_ACCEPTED``), whose
#: records simply carry no fsdp entries.
SHARD_PART_FORMAT = 2
_SHARD_PART_ACCEPTED = (1, 2)


def _host_view(x):
    """Host copy of a leaf.  A multi-process-sharded array (e.g. ZeRO-1
    optimizer state over a process-spanning mesh) is not fully
    addressable, so ``device_get`` would raise — gather it to its full
    global value first.  COLLECTIVE for such leaves: every process must
    reach this save on the same tick (true for the checkpointer and
    snapshot extensions, which trigger in lockstep).

    Trade-off, chosen for correctness + simplicity: the gather is a
    transient full-state materialisation per process and each per-rank
    shard file then holds the complete state (N× disk for N processes).
    Saving only the addressable shards and reassembling on load would
    restore 1/N files, at the cost of a resume protocol that must pair
    shard files with mesh positions — a future optimisation, noted here
    so nobody mistakes the current layout for it."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return x


def _leaf_crc(arr: np.ndarray) -> int:
    # C-contiguous view so the CRC covers the logical values, not an
    # arbitrary stride pattern (npz round-trips contiguous data anyway)
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def save_state(path: str, pytree, topology=None, shard_part=None) -> None:
    """Atomically write ``pytree`` (arrays / numeric scalars) to ``path``.

    ``topology`` (optional, a JSON-safe dict — see
    :func:`chainermn_tpu.training.elastic.topology_signature`) is stamped
    into the ``__meta__`` record so a resume at a DIFFERENT world size can
    probe what layout the shard was written under (:func:`read_topology`)
    without unpickling leaf data into a tree.  Snapshots without it load
    exactly as before — the stamp is additive.

    ``shard_part`` (optional, the record :func:`build_shard_part`
    returns) marks this file as ONE PART of a shard-only covering set;
    it rides the same CRC-guarded meta (:func:`read_shard_part`)."""
    from chainermn_tpu.utils.telemetry import get_recorder

    with get_recorder().span("checkpoint/save", cat="checkpoint",
                             path=os.path.basename(path)) as sp:
        leaves, treedef = jax.tree.flatten(
            jax.device_get(jax.tree.map(_host_view, pytree)))
        payload = {f"leaf_{i:05d}": np.asarray(v)
                   for i, v in enumerate(leaves)}
        # npz keeps only stock numpy dtypes; ml_dtypes leaves (bfloat16,
        # fp8) come back as raw void records — record true dtypes to
        # view-cast back.
        dtypes = [str(np.asarray(v).dtype) for v in leaves]
        crcs = [_leaf_crc(payload[f"leaf_{i:05d}"])
                for i in range(len(leaves))]
        meta = {"treedef": treedef, "dtypes": dtypes, "crcs": crcs,
                "meta_crc_excluded": True}
        if topology is not None:
            meta["topology"] = topology
        if shard_part is not None:
            meta["shard_part"] = shard_part
        meta_bytes = pickle.dumps(meta)
        # the meta record guards itself too: its own CRC rides in a
        # separate tiny array, so a flipped bit inside the pickle is a
        # typed error, not an unpickling crash
        payload["__meta__"] = np.frombuffer(meta_bytes, dtype=np.uint8)
        payload["__meta_crc__"] = np.asarray(
            [zlib.crc32(meta_bytes) & 0xFFFFFFFF], dtype=np.uint64)
        sp.set(n_leaves=len(leaves),
               nbytes=int(sum(p.nbytes for p in payload.values())))
        tmp = path + ".tmp"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)  # atomic on POSIX — no torn snapshots


def _read_meta(z, path: str) -> dict:
    """Decode + integrity-check the ``__meta__`` record of an open npz."""
    try:
        meta_arr = z["__meta__"]
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: snapshot has no readable __meta__ record "
            f"({type(e).__name__}: {e})") from e
    meta_bytes = meta_arr.tobytes()
    if "__meta_crc__" in getattr(z, "files", ()):
        want = int(z["__meta_crc__"][0])
        got = zlib.crc32(meta_bytes) & 0xFFFFFFFF
        if got != want:
            raise SnapshotCorruptError(
                f"{path}: __meta__ CRC mismatch "
                f"(recorded {want:#010x}, computed {got:#010x})")
    try:
        return pickle.loads(meta_bytes)
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: __meta__ record does not unpickle "
            f"({type(e).__name__}: {e})") from e


def _checked_leaves(z, meta: dict, path: str):
    """Yield ``(index, array)`` for every leaf, CRC-checked when the
    snapshot recorded checksums (older files without ``crcs`` load
    unchecked — forward-compatible resume)."""
    crcs = meta.get("crcs")
    for i in range(len(meta["dtypes"])):
        key = f"leaf_{i:05d}"
        try:
            arr = z[key]
        except Exception as e:
            raise SnapshotCorruptError(
                f"{path}: leaf {i} ({key}) unreadable "
                f"({type(e).__name__}: {e})") from e
        if crcs is not None:
            got = _leaf_crc(arr)
            if got != crcs[i]:
                raise SnapshotCorruptError(
                    f"{path}: leaf {i} CRC mismatch (recorded "
                    f"{crcs[i]:#010x}, computed {got:#010x}) — "
                    "shard bytes were corrupted on disk")
        yield i, arr


def verify_state(path: str) -> None:
    """Integrity probe: raise :class:`SnapshotCorruptError` if ``path``
    is not a complete, checksum-clean snapshot; return ``None`` when it
    is.  Reads every payload (same CRC walk as :func:`load_state`) but
    never unflattens a tree, so it is safe to run on snapshots written
    by a different model version.

    A MISSING file propagates as ``FileNotFoundError``, not as
    corruption — callers racing a concurrent GC (the checkpointer's
    verify pass on a shared filesystem) distinguish "gone" from
    "damaged": the first is skipped, only the second is quarantined."""
    from chainermn_tpu.utils.telemetry import get_recorder

    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: not a readable npz archive "
            f"({type(e).__name__}: {e})") from e
    with get_recorder().span("checkpoint/crc_walk", cat="checkpoint",
                             path=os.path.basename(path)), z:
        meta = _read_meta(z, path)
        for _ in _checked_leaves(z, meta, path):
            pass


def _read_meta_stamp(path: str, key: str):
    """One CRC-checked ``__meta__`` field of ``path`` — leaf payloads
    are never touched, so probing every candidate file of a resume
    costs one small read per file, not a full load.  Raises
    :class:`SnapshotCorruptError` on a damaged archive/meta;
    ``FileNotFoundError`` propagates ("gone" is not "damaged")."""
    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: not a readable npz archive "
            f"({type(e).__name__}: {e})") from e
    with z:
        return _read_meta(z, path).get(key)


def read_topology(path: str):
    """The topology signature stamped into ``path``'s ``__meta__`` (or
    ``None`` for snapshots written before the elastic-resume layer).
    Meta-only read — see :func:`_read_meta_stamp`."""
    return _read_meta_stamp(path, "topology")


def read_shard_part(path: str):
    """The ``shard_part`` record stamped into ``path``'s ``__meta__``
    (``None`` for ordinary full snapshots).  Meta-only read, like
    :func:`read_topology`."""
    return _read_meta_stamp(path, "shard_part")


def load_state(path: str):
    """Inverse of :func:`save_state`; returns the restored pytree.
    Raises :class:`SnapshotCorruptError` on any integrity failure."""
    return load_state_with_stamps(path)[0]


def load_state_with_topology(path: str):
    """Like :func:`load_state` but returns ``(pytree, topology)`` —
    the stamped signature comes from the same already-verified
    ``__meta__`` record, so the elastic resume path pays no second
    archive open (``None`` for pre-elastic snapshots)."""
    tree, topology, _ = load_state_with_stamps(path)
    return tree, topology


def load_state_with_stamps(path: str):
    """One checked read returning ``(pytree, topology, shard_part)`` —
    every stamp the multi-file resume path needs comes off the same
    verified ``__meta__`` record."""
    import ml_dtypes  # noqa: F401  (registers bfloat16/fp8 with numpy)

    from chainermn_tpu.utils.telemetry import get_recorder

    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise  # "gone" is not "damaged" — see verify_state
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: not a readable npz archive "
            f"({type(e).__name__}: {e})") from e
    with get_recorder().span("checkpoint/load", cat="checkpoint",
                             path=os.path.basename(path)) as sp, z:
        meta = _read_meta(z, path)
        leaves = []
        for i, arr in _checked_leaves(z, meta, path):
            want = np.dtype(meta["dtypes"][i])
            if arr.dtype != want:
                arr = arr.view(want)
            leaves.append(arr)
        sp.set(n_leaves=len(leaves))
    return (jax.tree.unflatten(meta["treedef"], leaves),
            meta.get("topology"), meta.get("shard_part"))


# --------------------------------------------------------------------- #
# shard-only save sets
# --------------------------------------------------------------------- #

def shard_leaf_indices(topology) -> list:
    """Flat ``opt_state`` leaf indices the topology signature's per-leaf
    layout marks as world-stacked parameter shards (``kind ==
    "shard"``) — the only leaves a shard-only set splits; everything
    else is replicated and rides the root part once."""
    layouts = (topology or {}).get("opt_leaves") or []
    return [i for i, spec in enumerate(layouts)
            if spec.get("kind") == "shard"]


def fsdp_leaf_entries(topology, key: str = "opt_leaves") -> list:
    """Flat ``(leaf index, shard dim)`` pairs for the dim-sharded
    ZeRO-3/FSDP leaves the topology signature records under ``key``
    (``"opt_leaves"`` or ``"param_leaves"`` — the unified layout
    table's ``{"kind": "fsdp", "dim": d}`` records).  Disjoint from
    :func:`shard_leaf_indices` by construction (one record per leaf,
    one kind per record)."""
    layouts = (topology or {}).get(key) or []
    return [(i, int(spec["dim"])) for i, spec in enumerate(layouts)
            if spec.get("kind") == "fsdp"]


def _dim_rows(leaf, lo: int, hi: int, world: int, dim: int):
    """Host copy of members ``[lo, hi)``'s slice of a dim-sharded
    (ZeRO-3/FSDP) leaf: elements ``[lo·L/W, hi·L/W)`` along ``dim``
    (``fsdp_dims`` guarantees ``L % W == 0``).

    Mirrors :func:`_member_rows` for the not-fully-addressable case:
    the slice is extracted from this process's addressable shards
    (only ``dim`` may be sharded — the fsdp layout's contract), and a
    request for members this process does not hold raises."""
    shape = tuple(np.shape(leaf))
    if dim < 0 or dim >= len(shape) or shape[dim] % world:
        raise ValueError(
            f"fsdp leaf has shape {shape}; expected dim {dim} "
            f"divisible by world {world}")
    w = shape[dim] // world
    a, b = lo * w, hi * w
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        out = np.empty(shape[:dim] + (b - a,) + shape[dim + 1:],
                       dtype=np.dtype(leaf.dtype))
        have = np.zeros((b - a,), bool)
        for sh in leaf.addressable_shards:
            idx = sh.index[dim]
            start = 0 if idx.start is None else idx.start
            stop = shape[dim] if idx.stop is None else idx.stop
            s, e = max(start, a), min(stop, b)
            if s < e:
                data = np.asarray(sh.data)
                sel_out = [slice(None)] * len(shape)
                sel_out[dim] = slice(s - a, e - a)
                sel_in = [slice(None)] * len(shape)
                sel_in[dim] = slice(s - start, e - start)
                out[tuple(sel_out)] = data[tuple(sel_in)]
                have[s - a:e - a] = True
        if not have.all():
            raise ValueError(
                f"members [{lo}, {hi})'s dim-{dim} slice is not "
                "addressable from this process — shard-only saves "
                "write only locally held slices")
        return out
    sel = [slice(None)] * len(shape)
    sel[dim] = slice(a, b)
    return np.asarray(np.asarray(leaf)[tuple(sel)])


def _member_rows(leaf, lo: int, hi: int, world: int):
    """Host copy of member rows ``[lo, hi)`` of a world-stacked leaf.

    For a process-spanning (not fully addressable) array the rows are
    extracted from this process's addressable shards — the point of
    shard-only saves is that nobody gathers the full state; a request
    for rows this process does not hold is a caller bug and raises."""
    shape = tuple(np.shape(leaf))
    if not shape or shape[0] != world:
        raise ValueError(
            f"shard leaf has shape {shape}; expected a leading "
            f"world axis of {world}")
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        out = np.empty((hi - lo,) + shape[1:],
                       dtype=np.dtype(leaf.dtype))
        have = np.zeros((hi - lo,), bool)
        for sh in leaf.addressable_shards:
            idx = sh.index[0]
            start = 0 if idx.start is None else idx.start
            stop = shape[0] if idx.stop is None else idx.stop
            a, b = max(start, lo), min(stop, hi)
            if a < b:
                data = np.asarray(sh.data)
                out[a - lo:b - lo] = data[a - start:b - start]
                have[a - lo:b - lo] = True
        if not have.all():
            raise ValueError(
                f"member rows [{lo}, {hi}) are not addressable from "
                "this process — shard-only saves write only locally "
                "held rows")
        return out
    return np.asarray(np.asarray(leaf)[lo:hi])


def build_shard_part(state: dict, topology: dict, lo: int, hi: int,
                     *, root: bool):
    """One part of a shard-only covering set: ``(part_state,
    shard_part_record)`` for member rows ``[lo, hi)``.

    The ROOT part is the full checkpointer state dict with every
    "shard"-kind ``opt_state`` leaf sliced down to its own rows; a
    non-root part carries ONLY ``{"shards": {leaf_XXXXX: rows}}``.
    The record names the covered range, the world, and the shard leaf
    indices, so :func:`assemble_shard_state` is self-describing —
    assembly never re-derives the layout from live code that may have
    moved on.

    ZeRO-3/FSDP topologies additionally record dim-sharded leaves
    (``{"kind": "fsdp"}`` in the layout table): those ``opt_state``
    leaves are sliced along their shard dim, and the PARAMS' fsdp
    leaves are sliced the same way (params are only 1/world resident
    per member at rest, so a full-param root would not exist anywhere).
    Non-root parts then also carry ``{"param_shards": {...}}``."""
    world = int(topology["world_size"])
    if not 0 <= lo < hi <= world:
        raise ValueError(f"member range [{lo}, {hi}) not in [0, {world})")
    idxs = shard_leaf_indices(topology)
    fsdp_opt = fsdp_leaf_entries(topology, "opt_leaves")
    fsdp_par = fsdp_leaf_entries(topology, "param_leaves")
    leaves, treedef = jax.tree.flatten(state["opt_state"])
    if fsdp_par:
        p_leaves, p_treedef = jax.tree.flatten(state["params"])
    if root:
        new = list(leaves)
        for i in idxs:
            new[i] = _member_rows(leaves[i], lo, hi, world)
        for i, dim in fsdp_opt:
            new[i] = _dim_rows(leaves[i], lo, hi, world, dim)
        part = dict(state)
        part["opt_state"] = jax.tree.unflatten(treedef, new)
        if fsdp_par:
            p_new = list(p_leaves)
            for i, dim in fsdp_par:
                p_new[i] = _dim_rows(p_leaves[i], lo, hi, world, dim)
            part["params"] = jax.tree.unflatten(p_treedef, p_new)
    else:
        shards = {f"leaf_{i:05d}": _member_rows(leaves[i], lo, hi, world)
                  for i in idxs}
        shards.update({f"leaf_{i:05d}":
                       _dim_rows(leaves[i], lo, hi, world, dim)
                       for i, dim in fsdp_opt})
        part = {"shards": shards}
        if fsdp_par:
            part["param_shards"] = {
                f"leaf_{i:05d}": _dim_rows(p_leaves[i], lo, hi, world, dim)
                for i, dim in fsdp_par}
    record = {"format": SHARD_PART_FORMAT, "members": [int(lo), int(hi)],
              "world": world, "root": bool(root),
              "shard_leaves": [int(i) for i in idxs]}
    if fsdp_opt or fsdp_par:
        record["fsdp_opt_leaves"] = [[int(i), int(d)] for i, d in fsdp_opt]
        record["fsdp_param_leaves"] = [[int(i), int(d)] for i, d in fsdp_par]
    else:
        # Pure row-sharded (ZeRO-1/2) sets keep the v1 record shape so
        # mixed-version fleets can still read each other's saves.
        record["format"] = 1
    return part, record


def assemble_shard_state(parts) -> dict:
    """Rebuild the full state dict from a COVERING set of shard-only
    parts (``(shard_part_record, part_state)`` pairs, any order).

    Verifies the set actually covers: exactly one root, member ranges
    tiling ``[0, world)`` with no gap or overlap, every part agreeing
    on world/format/leaf indices.  The result is BITWISE the state a
    full save would have written — each world-stacked shard leaf is the
    member-order concatenation of the parts' rows."""
    parts = list(parts)
    if not parts:
        raise ShardSetError("no shard parts to assemble")
    roots = [(rec, st) for rec, st in parts if rec.get("root")]
    if len(roots) != 1:
        raise ShardSetError(
            f"covering set needs exactly one root part, got "
            f"{len(roots)}")
    root_rec, root_state = roots[0]
    fmt = int(root_rec.get("format", -1))
    if fmt not in _SHARD_PART_ACCEPTED:
        raise ShardSetError(
            f"unknown shard_part format {root_rec.get('format')!r} "
            f"(this reader speaks {sorted(_SHARD_PART_ACCEPTED)})")
    world = int(root_rec["world"])
    idxs = [int(i) for i in root_rec["shard_leaves"]]
    fsdp_opt = [(int(i), int(d))
                for i, d in root_rec.get("fsdp_opt_leaves", [])]
    fsdp_par = [(int(i), int(d))
                for i, d in root_rec.get("fsdp_param_leaves", [])]
    ranges = []
    for rec, _ in parts:
        if int(rec.get("world", -1)) != world \
                or [int(i) for i in rec.get("shard_leaves", [])] != idxs \
                or [(int(i), int(d))
                    for i, d in rec.get("fsdp_opt_leaves", [])] != fsdp_opt \
                or [(int(i), int(d))
                    for i, d in rec.get("fsdp_param_leaves", [])] != fsdp_par \
                or int(rec.get("format", -1)) != fmt:
            raise ShardSetError(
                "shard parts disagree on world/leaf layout — files "
                "from different sets were mixed")
        ranges.append((int(rec["members"][0]), int(rec["members"][1])))
    order = sorted(range(len(parts)), key=lambda k: ranges[k])
    cursor = 0
    for k in order:
        lo, hi = ranges[k]
        if lo != cursor:
            raise ShardSetError(
                f"member ranges do not tile [0, {world}): gap or "
                f"overlap at member {cursor} (next part covers "
                f"[{lo}, {hi}))")
        cursor = hi
    if cursor != world:
        raise ShardSetError(
            f"member ranges stop at {cursor}, but the set's world is "
            f"{world} — the covering set is incomplete")
    def _collect(i, container_key, state_key):
        key = f"leaf_{i:05d}"
        rows = []
        for k in order:
            rec, st = parts[k]
            if rec.get("root"):
                sub, _ = jax.tree.flatten(st[state_key])
                rows.append(np.asarray(sub[i]))
            else:
                try:
                    rows.append(np.asarray(st[container_key][key]))
                except KeyError:
                    raise ShardSetError(
                        f"part covering {rec['members']} is missing "
                        f"shard leaf {key}") from None
        return rows

    leaves, treedef = jax.tree.flatten(root_state["opt_state"])
    new = list(leaves)
    for i in idxs:
        new[i] = np.concatenate(_collect(i, "shards", "opt_state"), axis=0)
    for i, dim in fsdp_opt:
        new[i] = np.concatenate(_collect(i, "shards", "opt_state"),
                                axis=dim)
    out = dict(root_state)
    out["opt_state"] = jax.tree.unflatten(treedef, new)
    if fsdp_par:
        p_leaves, p_treedef = jax.tree.flatten(root_state["params"])
        p_new = list(p_leaves)
        for i, dim in fsdp_par:
            p_new[i] = np.concatenate(
                _collect(i, "param_shards", "params"), axis=dim)
        out["params"] = jax.tree.unflatten(p_treedef, p_new)
    return out
