"""Pytree snapshot serialization — the package's own serializer (the
reference leaned on ``chainer.serializers.save_npz``; SURVEY §7 step 4 calls
for an orbax-style layout but our own implementation, no orbax dependency).

Format: one ``.npz`` per snapshot holding every leaf as a named array
(``leaf_00000``, ...) plus the pickled treedef — self-contained, atomic
(write to ``.tmp`` then rename), resumable within the same code version.
Device arrays are pulled to host with ``jax.device_get`` so saving works
for sharded/replicated params alike (each process saves its addressable
view — the per-process *shard* file of the multi-node checkpointer).
"""

from __future__ import annotations

import os
import pickle

import jax
import numpy as np

__all__ = ["save_state", "load_state"]


def _host_view(x):
    """Host copy of a leaf.  A multi-process-sharded array (e.g. ZeRO-1
    optimizer state over a process-spanning mesh) is not fully
    addressable, so ``device_get`` would raise — gather it to its full
    global value first.  COLLECTIVE for such leaves: every process must
    reach this save on the same tick (true for the checkpointer and
    snapshot extensions, which trigger in lockstep).

    Trade-off, chosen for correctness + simplicity: the gather is a
    transient full-state materialisation per process and each per-rank
    shard file then holds the complete state (N× disk for N processes).
    Saving only the addressable shards and reassembling on load would
    restore 1/N files, at the cost of a resume protocol that must pair
    shard files with mesh positions — a future optimisation, noted here
    so nobody mistakes the current layout for it."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return x


def save_state(path: str, pytree) -> None:
    """Atomically write ``pytree`` (arrays / numeric scalars) to ``path``."""
    leaves, treedef = jax.tree.flatten(
        jax.device_get(jax.tree.map(_host_view, pytree)))
    payload = {f"leaf_{i:05d}": np.asarray(v) for i, v in enumerate(leaves)}
    # npz keeps only stock numpy dtypes; ml_dtypes leaves (bfloat16, fp8)
    # come back as raw void records — record true dtypes to view-cast back.
    dtypes = [str(np.asarray(v).dtype) for v in leaves]
    payload["__meta__"] = np.frombuffer(
        pickle.dumps({"treedef": treedef, "dtypes": dtypes}), dtype=np.uint8)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic on POSIX — no torn snapshots


def load_state(path: str):
    """Inverse of :func:`save_state`; returns the restored pytree."""
    import ml_dtypes  # noqa: F401  (registers bfloat16/fp8 with numpy)

    with np.load(path, allow_pickle=False) as z:
        meta = pickle.loads(z["__meta__"].tobytes())
        leaves = []
        for i, dt in enumerate(meta["dtypes"]):
            arr = z[f"leaf_{i:05d}"]
            want = np.dtype(dt)
            if arr.dtype != want:
                arr = arr.view(want)
            leaves.append(arr)
    return jax.tree.unflatten(meta["treedef"], leaves)
