"""Pytree snapshot serialization — the package's own serializer (the
reference leaned on ``chainer.serializers.save_npz``; SURVEY §7 step 4 calls
for an orbax-style layout but our own implementation, no orbax dependency).

Format: one ``.npz`` per snapshot holding every leaf as a named array
(``leaf_00000``, ...) plus the pickled treedef — self-contained, atomic
(write to ``.tmp`` then rename), resumable within the same code version.
Device arrays are pulled to host with ``jax.device_get`` so saving works
for sharded/replicated params alike (each process saves its addressable
view — the per-process *shard* file of the multi-node checkpointer).

Integrity: every payload (each leaf's raw bytes and the meta record
itself) carries a CRC32 recorded inside ``__meta__``, so a torn write
the atomic rename could not prevent (disk-full, power cut mid-fsync) or
silent bit rot is DETECTED at load instead of surfacing as an opaque
npz/pickle error deep inside resume.  :func:`verify_state` probes a file
without unpickling leaf data into a tree; :func:`load_state` checks the
same CRCs on its real read path.  Corruption raises the typed
:class:`SnapshotCorruptError` — the checkpointer's fallback-resume path
catches exactly that (docs/RESILIENCE.md).
"""

from __future__ import annotations

import os
import pickle
import zlib

import jax
import numpy as np

__all__ = ["SnapshotCorruptError", "load_state",
           "load_state_with_topology", "read_topology", "save_state",
           "verify_state"]


class SnapshotCorruptError(RuntimeError):
    """A snapshot file failed its integrity check (bad CRC, missing
    leaf, undecodable meta, truncated archive).  Typed so recovery code
    (``MultiNodeCheckpointer.maybe_load`` fallback) can distinguish
    "this file is damaged" from programming errors."""


def _host_view(x):
    """Host copy of a leaf.  A multi-process-sharded array (e.g. ZeRO-1
    optimizer state over a process-spanning mesh) is not fully
    addressable, so ``device_get`` would raise — gather it to its full
    global value first.  COLLECTIVE for such leaves: every process must
    reach this save on the same tick (true for the checkpointer and
    snapshot extensions, which trigger in lockstep).

    Trade-off, chosen for correctness + simplicity: the gather is a
    transient full-state materialisation per process and each per-rank
    shard file then holds the complete state (N× disk for N processes).
    Saving only the addressable shards and reassembling on load would
    restore 1/N files, at the cost of a resume protocol that must pair
    shard files with mesh positions — a future optimisation, noted here
    so nobody mistakes the current layout for it."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return x


def _leaf_crc(arr: np.ndarray) -> int:
    # C-contiguous view so the CRC covers the logical values, not an
    # arbitrary stride pattern (npz round-trips contiguous data anyway)
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def save_state(path: str, pytree, topology=None) -> None:
    """Atomically write ``pytree`` (arrays / numeric scalars) to ``path``.

    ``topology`` (optional, a JSON-safe dict — see
    :func:`chainermn_tpu.training.elastic.topology_signature`) is stamped
    into the ``__meta__`` record so a resume at a DIFFERENT world size can
    probe what layout the shard was written under (:func:`read_topology`)
    without unpickling leaf data into a tree.  Snapshots without it load
    exactly as before — the stamp is additive."""
    from chainermn_tpu.utils.telemetry import get_recorder

    with get_recorder().span("checkpoint/save", cat="checkpoint",
                             path=os.path.basename(path)) as sp:
        leaves, treedef = jax.tree.flatten(
            jax.device_get(jax.tree.map(_host_view, pytree)))
        payload = {f"leaf_{i:05d}": np.asarray(v)
                   for i, v in enumerate(leaves)}
        # npz keeps only stock numpy dtypes; ml_dtypes leaves (bfloat16,
        # fp8) come back as raw void records — record true dtypes to
        # view-cast back.
        dtypes = [str(np.asarray(v).dtype) for v in leaves]
        crcs = [_leaf_crc(payload[f"leaf_{i:05d}"])
                for i in range(len(leaves))]
        meta = {"treedef": treedef, "dtypes": dtypes, "crcs": crcs,
                "meta_crc_excluded": True}
        if topology is not None:
            meta["topology"] = topology
        meta_bytes = pickle.dumps(meta)
        # the meta record guards itself too: its own CRC rides in a
        # separate tiny array, so a flipped bit inside the pickle is a
        # typed error, not an unpickling crash
        payload["__meta__"] = np.frombuffer(meta_bytes, dtype=np.uint8)
        payload["__meta_crc__"] = np.asarray(
            [zlib.crc32(meta_bytes) & 0xFFFFFFFF], dtype=np.uint64)
        sp.set(n_leaves=len(leaves),
               nbytes=int(sum(p.nbytes for p in payload.values())))
        tmp = path + ".tmp"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)  # atomic on POSIX — no torn snapshots


def _read_meta(z, path: str) -> dict:
    """Decode + integrity-check the ``__meta__`` record of an open npz."""
    try:
        meta_arr = z["__meta__"]
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: snapshot has no readable __meta__ record "
            f"({type(e).__name__}: {e})") from e
    meta_bytes = meta_arr.tobytes()
    if "__meta_crc__" in getattr(z, "files", ()):
        want = int(z["__meta_crc__"][0])
        got = zlib.crc32(meta_bytes) & 0xFFFFFFFF
        if got != want:
            raise SnapshotCorruptError(
                f"{path}: __meta__ CRC mismatch "
                f"(recorded {want:#010x}, computed {got:#010x})")
    try:
        return pickle.loads(meta_bytes)
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: __meta__ record does not unpickle "
            f"({type(e).__name__}: {e})") from e


def _checked_leaves(z, meta: dict, path: str):
    """Yield ``(index, array)`` for every leaf, CRC-checked when the
    snapshot recorded checksums (older files without ``crcs`` load
    unchecked — forward-compatible resume)."""
    crcs = meta.get("crcs")
    for i in range(len(meta["dtypes"])):
        key = f"leaf_{i:05d}"
        try:
            arr = z[key]
        except Exception as e:
            raise SnapshotCorruptError(
                f"{path}: leaf {i} ({key}) unreadable "
                f"({type(e).__name__}: {e})") from e
        if crcs is not None:
            got = _leaf_crc(arr)
            if got != crcs[i]:
                raise SnapshotCorruptError(
                    f"{path}: leaf {i} CRC mismatch (recorded "
                    f"{crcs[i]:#010x}, computed {got:#010x}) — "
                    "shard bytes were corrupted on disk")
        yield i, arr


def verify_state(path: str) -> None:
    """Integrity probe: raise :class:`SnapshotCorruptError` if ``path``
    is not a complete, checksum-clean snapshot; return ``None`` when it
    is.  Reads every payload (same CRC walk as :func:`load_state`) but
    never unflattens a tree, so it is safe to run on snapshots written
    by a different model version.

    A MISSING file propagates as ``FileNotFoundError``, not as
    corruption — callers racing a concurrent GC (the checkpointer's
    verify pass on a shared filesystem) distinguish "gone" from
    "damaged": the first is skipped, only the second is quarantined."""
    from chainermn_tpu.utils.telemetry import get_recorder

    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: not a readable npz archive "
            f"({type(e).__name__}: {e})") from e
    with get_recorder().span("checkpoint/crc_walk", cat="checkpoint",
                             path=os.path.basename(path)), z:
        meta = _read_meta(z, path)
        for _ in _checked_leaves(z, meta, path):
            pass


def read_topology(path: str):
    """The topology signature stamped into ``path``'s ``__meta__`` (or
    ``None`` for snapshots written before the elastic-resume layer).
    Reads and CRC-checks only the meta record — leaf payloads are never
    touched, so probing every candidate shard of a resize resume costs
    one small read per file, not a full load.  Raises
    :class:`SnapshotCorruptError` on a damaged archive/meta;
    ``FileNotFoundError`` propagates ("gone" is not "damaged")."""
    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: not a readable npz archive "
            f"({type(e).__name__}: {e})") from e
    with z:
        return _read_meta(z, path).get("topology")


def load_state(path: str):
    """Inverse of :func:`save_state`; returns the restored pytree.
    Raises :class:`SnapshotCorruptError` on any integrity failure."""
    return load_state_with_topology(path)[0]


def load_state_with_topology(path: str):
    """Like :func:`load_state` but returns ``(pytree, topology)`` —
    the stamped signature comes from the same already-verified
    ``__meta__`` record, so the elastic resume path pays no second
    archive open (``None`` for pre-elastic snapshots)."""
    import ml_dtypes  # noqa: F401  (registers bfloat16/fp8 with numpy)

    from chainermn_tpu.utils.telemetry import get_recorder

    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise  # "gone" is not "damaged" — see verify_state
    except Exception as e:
        raise SnapshotCorruptError(
            f"{path}: not a readable npz archive "
            f"({type(e).__name__}: {e})") from e
    with get_recorder().span("checkpoint/load", cat="checkpoint",
                             path=os.path.basename(path)) as sp, z:
        meta = _read_meta(z, path)
        leaves = []
        for i, arr in _checked_leaves(z, meta, path):
            want = np.dtype(meta["dtypes"][i])
            if arr.dtype != want:
                arr = arr.view(want)
            leaves.append(arr)
        sp.set(n_leaves=len(leaves))
    return (jax.tree.unflatten(meta["treedef"], leaves),
            meta.get("topology"))
