"""Communication-volume model: per-collective bytes/step from compiled HLO.

The reference's scaling story (ChainerMN's ~90%-efficiency ImageNet
claims, SURVEY.md §6) was argued from measured multi-node runs; this
container has ONE real chip, so the equivalent evidence chain here is
analytic: walk a compiled step's HLO for collective ops, count the bytes
each moves, convert to wire time with the standard ring formulas and the
interconnect's published bandwidth, and compare against the measured
single-chip step time.  ``SCALING.md`` assembles the result.

Axis attribution: a composed-mesh HLO doesn't name mesh axes, so
:func:`axis_collective_report` compiles the SAME step on single-active-
axis virtual meshes (e.g. ``data=8``, then ``model=8``) — every
collective in that program belongs to that axis.  This is exact for the
per-axis *volume model* because collective volume depends only on the
axis being reduced/gathered over, not on which other axes exist.

Wire-cost conventions (ring algorithms, ``n`` = axis size, ``s`` =
tensor bytes): all-reduce moves ``2s(n-1)/n`` per device, all-gather and
reduce-scatter ``s(n-1)/n`` (s = the FULL tensor), all-to-all
``s(n-1)/n``, collective-permute ``s``.  XLA may pick tree variants on
real topologies; ring is the bandwidth-optimal baseline the model uses.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = [
    "CollectiveStats",
    "LinkParams",
    "PRIMITIVE_WIRE_KINDS",
    "collective_stats",
    "stablehlo_collective_stats",
    "primitive_cost",
    "program_cost",
    "wire_bytes_per_device",
    "axis_collective_report",
    "choose_accum_steps",
    "choose_bucket_bytes",
    "choose_gather_prefetch_depth",
    "choose_prefetch_depth",
    "fused_collective_budget",
    "overlap_exposed_time",
    "assert_fused_collectives",
    "assert_accum_collectives",
    "assert_overlap_collectives",
]

# Interconnect defaults for choose_bucket_bytes: per-collective launch
# latency and per-device ring bandwidth.  ICI-flavoured (TPU v4/v5
# publish ~100 GB/s per link; a few microseconds to get a collective
# onto the wire) — pass measured values for other fabrics (DCN: ~25 us,
# ~12.5 GB/s per NIC).
_DEFAULT_LATENCY_S = 2e-6
_DEFAULT_BANDWIDTH = 90e9


@dataclass(frozen=True)
class LinkParams:
    """Interconnect constants the analytic models consume: per-collective
    launch latency (seconds) and per-device ring bandwidth (bytes/s).

    The defaults baked into :func:`choose_bucket_bytes` /
    :func:`choose_accum_steps` are PUBLISHED ICI numbers; this carrier
    exists so the measured autotuner (``utils/autotune.py``) can hand
    those models constants fitted from its own probe timings on the
    live machine — the plan then both picks the exchange strategy
    empirically AND recalibrates every later analytic decision
    (``choose_bucket_bytes``, ``choose_accum_steps``) to the real
    fabric.
    """

    latency_s: float = _DEFAULT_LATENCY_S
    bandwidth_bytes_per_s: float = _DEFAULT_BANDWIDTH

    @classmethod
    def from_probes(cls, samples) -> "LinkParams":
        """Least-squares fit of ``t = launches * alpha + wire_bytes /
        beta`` over probe timings.

        ``samples`` is an iterable of ``(n_launches, wire_bytes,
        seconds)`` rows — one per timed exchange candidate (the
        autotuner knows each candidate's collective count and ring
        bytes analytically, and measures its wall time).  Solves the
        2-unknown normal equations for ``alpha`` (latency) and
        ``1/beta`` (inverse bandwidth); a degenerate or unphysical fit
        (fewer than 2 distinct rows, singular system, non-positive
        constants) falls back to the published defaults — measured
        constants must never be WORSE than no measurement.
        """
        rows = [(float(k), float(b), float(t)) for k, b, t in samples
                if t > 0 and (k > 0 or b > 0)]
        if len(rows) < 2:
            return cls()
        # normal equations for t ~ k*alpha + b*inv_beta
        skk = sum(k * k for k, _, _ in rows)
        sbb = sum(b * b for _, b, _ in rows)
        skb = sum(k * b for k, b, _ in rows)
        skt = sum(k * t for k, _, t in rows)
        sbt = sum(b * t for _, b, t in rows)
        det = skk * sbb - skb * skb
        if abs(det) < 1e-30:
            return cls()
        alpha = (skt * sbb - sbt * skb) / det
        inv_beta = (sbt * skk - skt * skb) / det
        if alpha <= 0 or inv_beta <= 0:
            return cls()
        return cls(latency_s=alpha, bandwidth_bytes_per_s=1.0 / inv_beta)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_KINDS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
          "collective-permute")

# one HLO instruction: "%name = SHAPE kind(...)" where SHAPE is a single
# "f32[8,16]{...}" or a tuple "(f32[8]{..}, bf16[4,4]{..})"; -start
# variants are the async halves (count those, skip -done duplicates)
_INSTR_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9]+\[[^\]]*\]\S*)\s+"
    r"(" + "|".join(_KINDS) + r")(-start)?\(")

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
# iota form: replica_groups=[num_groups,group_size]<=[...]
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")


def _shape_bytes(shape_str: str, is_start: bool = False) -> int:
    shapes = _SHAPE_RE.findall(shape_str)
    if is_start and len(shapes) >= 2:
        # async start ops carry (operands, results, context...) in one
        # tuple; counting the whole tuple would double the volume.
        # Element 1 is the result buffer (element 0 the operand).
        shapes = shapes[1:2]
    total = 0
    for dtype, dims in shapes:
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _group_size(line: str) -> Optional[int]:
    m = _GROUPS_RE.search(line)
    if m:
        first = m.group(1).split("}")[0].lstrip("{")
        ids = [t for t in first.split(",") if t.strip()]
        return len(ids) or None
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        return int(m.group(2)) or None
    return None


@dataclass
class CollectiveStats:
    """Aggregate of one collective kind in one compiled program."""

    kind: str
    count: int = 0
    bytes: int = 0              # summed tensor bytes across call sites
    group_size: Optional[int] = None   # replica-group size (if uniform)
    looped: int = 0             # call sites inside a while-loop body:
    #                             they run once PER TRIP, so a per-window
    #                             count must treat them separately (the
    #                             accumulation proof hinges on this)
    async_depth: int = 0        # async -start/-done pairs with at least
    #                             one OTHER instruction scheduled between
    #                             the halves: collectives the backend
    #                             actually runs concurrently with compute
    #                             (sync lowerings — XLA:CPU today — and
    #                             back-to-back start;done pairs score 0)

    def wire_bytes(self, axis_size: Optional[int] = None) -> float:
        n = axis_size or self.group_size
        if n is None or n < 1:
            # never guess: a silently-wrong group size corrupts the
            # whole wire-volume evidence chain
            raise ValueError(
                "replica group size unknown (unparsed or non-uniform "
                "replica_groups); pass axis_size explicitly")
        full = self.bytes
        if self.kind == "reduce-scatter":
            # HLO records the SCATTERED output shape (1/n of the full
            # tensor); the wire formulas want the full tensor
            full = self.bytes * n
        return wire_bytes_per_device(self.kind, full, n)


def wire_bytes_per_device(kind: str, tensor_bytes: float, n: int) -> float:
    """Ring-algorithm bytes each device moves for ``tensor_bytes`` of
    payload over an ``n``-member group (see module docstring)."""
    if n <= 1:
        return 0.0
    frac = (n - 1) / n
    if kind == "all-reduce":
        return 2.0 * tensor_bytes * frac
    if kind in ("all-gather", "reduce-scatter", "all-to-all"):
        return tensor_bytes * frac
    if kind == "collective-permute":
        return float(tensor_bytes)
    raise ValueError(f"unknown collective kind {kind!r}")


# ---------------------------------------------------------------------
# per-primitive cost terms for the collective-plan IR
# (``ops.plan_ir``): maps each wire primitive to its ring wire-bytes
# formula so the pattern autotuner's pruning covers all-to-all and
# ppermute/send_recv, not just the allreduce strategy space
# ---------------------------------------------------------------------

PRIMITIVE_WIRE_KINDS = {
    "all_reduce": "all-reduce",
    "reduce_scatter": "reduce-scatter",
    "all_gather": "all-gather",
    "all_to_all": "all-to-all",
    "ppermute": "collective-permute",
    "send_recv": "collective-permute",
}


def primitive_cost(op: str, tensor_bytes: float, axis_size: int, *,
                   launches: int = 1, link: Optional[LinkParams] = None) \
        -> float:
    """Modeled seconds for one plan-IR primitive step moving
    ``tensor_bytes`` of payload over an ``axis_size``-member group in
    ``launches`` separate collective launches.  Non-wire primitives
    (``fuse`` / ``cast_wire`` / ``barrier``) cost zero — they are
    on-device data movement the wire model does not see."""
    kind = PRIMITIVE_WIRE_KINDS.get(op)
    if kind is None:
        return 0.0
    link = link or LinkParams()
    wire = wire_bytes_per_device(kind, float(tensor_bytes),
                                 int(axis_size))
    return (max(int(launches), 1) * link.latency_s
            + wire / link.bandwidth_bytes_per_s)


def program_cost(steps, tensor_bytes: float, axis_sizes: Dict[str, int],
                 *, link: Optional[LinkParams] = None) -> float:
    """Modeled seconds for a whole plan-IR program: the sum of its
    steps' :func:`primitive_cost` terms.  ``steps`` is an iterable of
    dict-likes with ``op``, ``axis`` (a role key into ``axis_sizes``),
    and optional ``launches`` / ``bytes_scale`` (wire-dtype shrink)
    enrichments the autotuner derives from the payload signature."""
    total = 0.0
    for st in steps:
        n = int(axis_sizes.get(st.get("axis") or "main", 1))
        total += primitive_cost(
            st["op"], float(tensor_bytes) * float(
                st.get("bytes_scale", 1.0)),
            n, launches=int(st.get("launches", 1)), link=link)
    return total


# computation header: "%name (params) -> type {" (possibly "ENTRY %...")
# — instruction lines carry "name = " before the first "(", headers
# never do, which is how the two are told apart
_COMP_HEADER_RE = re.compile(r"^\s*(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
# computations an instruction hands control to (while bodies/conditions,
# fusions, reducers, conditionals, async wrappers)
_COMP_REF_RE = re.compile(
    r"(?:body|condition|to_apply|calls|true_computation|"
    r"false_computation)=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
# body AND condition both execute once per trip (the condition once
# more); a collective in either is a per-iteration collective
_WHILE_PARTS_RE = re.compile(
    r"=[^=]*\bwhile\(.*?(?:body|condition)=%?([\w.\-]+)"
    r"(?:.*?(?:body|condition)=%?([\w.\-]+))?")


def _split_computations(text: str) -> Dict[str, list]:
    """HLO module text -> {computation name: [instruction lines]}.
    Lines outside any recognised computation land under ``""``."""
    comps: Dict[str, list] = {}
    current = ""
    for line in text.splitlines():
        head = _COMP_HEADER_RE.match(line)
        if head is not None and "=" not in line.split("(", 1)[0]:
            current = head.group(1)
            comps.setdefault(current, [])
            continue
        if line.strip().startswith("}"):
            current = ""
            continue
        comps.setdefault(current, []).append(line)
    return comps


def _loop_body_computations(comps: Dict[str, list]) -> set:
    """Names of computations reachable from any ``while`` body or
    condition — a collective there executes once per trip, not once
    per call."""
    refs: Dict[str, set] = {}
    bodies: set = set()
    for name, lines in comps.items():
        refs[name] = set()
        for line in lines:
            w = _WHILE_PARTS_RE.search(line)
            if w:
                bodies.update(g for g in w.groups() if g)
            refs[name].update(_COMP_REF_RE.findall(line))
            for blob in _BRANCHES_RE.findall(line):
                refs[name].update(
                    t.strip().lstrip("%") for t in blob.split(",")
                    if t.strip())
    reach, frontier = set(), list(bodies)
    while frontier:
        c = frontier.pop()
        if c in reach:
            continue
        reach.add(c)
        frontier.extend(refs.get(c, ()))
    return reach


_LHS_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")
_DONE_RE = re.compile(r"(" + "|".join(_KINDS) + r")-done\(")


def _hlo_texts(compiled) -> list:
    """The optimised (scheduled) HLO module texts of a
    ``jax.stages.Compiled`` — instruction order in each computation is
    the backend's execution schedule, which is what the overlap proof
    reads."""
    try:
        return [m.to_string() for m in compiled.runtime_executable()
                .hlo_modules()]
    except Exception:
        return [compiled.as_text()]


def collective_stats(compiled) -> Dict[str, CollectiveStats]:
    """Parse a ``jax.stages.Compiled``'s HLO for collectives.

    Returns ``{kind: CollectiveStats}``.  Bytes are the OUTPUT tensor
    sizes at each call site (for all-gather that is the gathered size,
    matching the wire formulas' conventions); async ``-start``/``-done``
    pairs are counted once.  A collective inside a ``while`` body (e.g.
    a pipeline scan) appears once in HLO but runs per iteration — such
    call sites are tallied in ``.looped`` (as well as ``.count``), so
    callers can scale by the trip count, and
    :func:`assert_accum_collectives` can prove a scan body exchanges
    NOTHING.

    Async depth: every ``-start`` whose matching ``-done`` is scheduled
    with at least one other instruction between the halves bumps its
    kind's ``.async_depth`` — the count of collectives the backend
    actually overlaps with other work, as opposed to merely emitting
    (:func:`assert_overlap_collectives` and ``bench_overlap.py`` read
    this alongside the schedule-position evidence).
    """
    out: Dict[str, CollectiveStats] = {}
    for text in _hlo_texts(compiled):
        comps = _split_computations(text)
        looped_comps = _loop_body_computations(comps)
        for comp_name, lines in comps.items():
            in_loop = comp_name in looped_comps
            pending: Dict[str, tuple] = {}    # lhs -> (stats, instr_idx)
            n_instr = 0
            for line in lines:
                lhs = _LHS_RE.match(line)
                if lhs is not None:
                    n_instr += 1
                if pending and _DONE_RE.search(line):
                    for name in list(pending):
                        # exact-token match: HLO names may contain
                        # [\w.-], and XLA's ".N" suffixing makes one
                        # start's name a PREFIX of another's — a \b
                        # boundary would pop %all-reduce-start on the
                        # done line of %all-reduce-start.1
                        if re.search(r"%" + re.escape(name)
                                     + r"(?![\w.\-])", line):
                            st, s_idx = pending.pop(name)
                            if n_instr - s_idx > 1:
                                st.async_depth += 1
                            break
                m = _INSTR_RE.search(line)
                if not m:
                    continue
                shape_str, kind = m.group(1), m.group(2)
                g = _group_size(line)
                if g == 1:
                    # singleton replica groups come from size-1 mesh axes
                    # (the one-code-path-for-every-mesh-shape discipline);
                    # they move zero wire bytes — skip, don't pollute
                    continue
                st = out.setdefault(kind, CollectiveStats(kind))
                st.count += 1
                st.looped += int(in_loop)
                st.bytes += _shape_bytes(shape_str,
                                         is_start=bool(m.group(3)))
                if g is not None:
                    st.group_size = g if st.group_size in (None, g) else -1
                if m.group(3) and lhs is not None:
                    pending[lhs.group(1)] = (st, n_instr)
    return out


_SHLO_KIND = {
    "all_reduce": "all-reduce", "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter", "all_to_all": "all-to-all",
    "collective_permute": "collective-permute",
}
_SHLO_RE = re.compile(
    r"stablehlo\.(" + "|".join(_SHLO_KIND) + r")\"?[(<]")
_SHLO_TENSOR_RE = re.compile(r"tensor<([0-9x]*)x?([a-z][a-z0-9]*)>")
_SHLO_DTYPE_BYTES = {
    "i1": 1, "i8": 1, "ui8": 1, "f8e4m3": 1, "f8e5m2": 1,
    "i16": 2, "ui16": 2, "f16": 2, "bf16": 2,
    "i32": 4, "ui32": 4, "f32": 4,
    "i64": 8, "ui64": 8, "f64": 8,
}
_SHLO_GROUPS_RE = re.compile(
    r"replica_groups\s*=\s*dense<[^>]*>\s*:\s*tensor<([0-9]+)x([0-9]+)x")
_SHLO_FUNC_RE = re.compile(r"func\.func\b[^@]*@([\w$.\-]+)\s*\(")
_SHLO_CALL_RE = re.compile(r"\bcall\s+@([\w$.\-]+)")


def stablehlo_collective_stats(lowered_text: str) \
        -> Dict[str, CollectiveStats]:
    """Like :func:`collective_stats` but over ``fn.lower(...).as_text()``
    (StableHLO) — the program JAX hands the compiler, BEFORE backend
    legalisation.  This is the dtype-true view: XLA:CPU widens bf16
    collectives to f32 (no bf16 kernels), so wire-compression modelling
    must read StableHLO; the optimised-HLO parser remains the
    backend-truth cross-check for counts.  Caveat: pre-optimisation,
    so collectives that XLA would DCE still show up here.
    """
    out: Dict[str, CollectiveStats] = {}
    lines = lowered_text.splitlines()
    # Loop attribution needs TWO mechanisms in StableHLO: the while op
    # carries cond/body as INLINE regions (a brace-depth interval — a
    # stack of [depth-before-the-while, region-has-opened] entries,
    # nesting-safe, opened-flag surviving the pretty form whose region
    # braces open on later lines), but jax outlines scan bodies into
    # private func.funcs the while region merely `call`s — so functions
    # transitively reachable from any in-while call site are looped
    # too.  Structural pre-pass; the collective pass below reads it.
    depth = 0
    while_stack: list = []
    cur_fn = ""
    line_ctx = []                     # (enclosing fn, inline-in-while)
    fn_calls: Dict[str, set] = {}     # fn -> {callee}
    looped_seed = set()               # callees called from a while
    for line in lines:
        fm = _SHLO_FUNC_RE.search(line)
        if fm:
            cur_fn = fm.group(1)
            while_stack = []
        in_while = bool(while_stack)
        if "stablehlo.while" in line:
            while_stack.append([depth, "{" in line])
        depth += line.count("{") - line.count("}")
        for entry in while_stack:
            if depth > entry[0]:
                entry[1] = True
        while while_stack and while_stack[-1][1] \
                and depth <= while_stack[-1][0]:
            while_stack.pop()
        cm = _SHLO_CALL_RE.search(line)
        if cm:
            fn_calls.setdefault(cur_fn, set()).add(cm.group(1))
            if in_while:
                looped_seed.add(cm.group(1))
        line_ctx.append((cur_fn, in_while))
    looped_fns, frontier = set(), list(looped_seed)
    while frontier:
        f = frontier.pop()
        if f in looped_fns:
            continue
        looped_fns.add(f)
        frontier.extend(fn_calls.get(f, ()))
    for i, line in enumerate(lines):
        m = _SHLO_RE.search(line)
        if not m:
            continue
        kind = _SHLO_KIND[m.group(1)]
        gm = _SHLO_GROUPS_RE.search(line)
        gsize = int(gm.group(2)) if gm else None
        if gsize == 1:
            continue        # size-1 mesh axis: zero-wire no-op
        # Result type: region-carrying ops (all_reduce/reduce_scatter
        # wrap their reduction computation in `({ ... })`) put the
        # `(operand) -> result` signature on the line that CLOSES the
        # region, not the op line — and the op line's last tensor<>
        # would be the replica_groups attribute (i64!).  Scan forward
        # to the signature line when `->` isn't present here.
        sig = line
        if "->" not in sig:
            for j in range(i + 1, min(i + 50, len(lines))):
                if "}) :" in lines[j] and "->" in lines[j]:
                    sig = lines[j]
                    break
            else:
                continue
        tail = sig.split("->", 1)[1]
        shapes = _SHLO_TENSOR_RE.findall(tail)
        if not shapes:
            continue
        dims_s, dtype = shapes[0]
        if dtype not in _SHLO_DTYPE_BYTES:
            continue
        n = 1
        for d in dims_s.split("x"):
            if d:
                n *= int(d)
        fn, inline_in_while = line_ctx[i]
        st = out.setdefault(kind, CollectiveStats(kind))
        st.count += 1
        st.looped += int(inline_in_while or fn in looped_fns)
        st.bytes += n * _SHLO_DTYPE_BYTES[dtype]
        if gsize is not None:
            st.group_size = gsize if st.group_size in (None, gsize) \
                else -1
    return out


def choose_bucket_bytes(
    total_bytes: float,
    axis_size: int,
    latency_s: float = _DEFAULT_LATENCY_S,
    bandwidth_bytes_per_s: float = _DEFAULT_BANDWIDTH,
    min_bucket: int = 256 * 1024,
    link: Optional[LinkParams] = None,
) -> int:
    """Principled fused-allreduce bucket size from the latency-bandwidth
    model — the ``allreduce_grad_dtype``-era tuning knob made analytic.

    With ``k = ceil(G/b)`` buckets over ``G`` total gradient bytes, the
    exposed cost the bucket size controls is

        ``T(b) = (G/b) * alpha  +  2 b (n-1)/(n * beta)``

    — every bucket pays launch latency ``alpha``, while only the *last*
    bucket's ring time ``2b(n-1)/(n*beta)`` is exposed once buckets
    pipeline against compute/each other (one big bucket maximally delays
    the first byte; per-leaf buckets pay latency hundreds of times —
    exactly the regime this subsystem replaces).  Minimising T gives

        ``b* = sqrt( G * alpha * n * beta / (2 (n-1)) )``

    clamped to ``[min_bucket, G]``.  Defaults model ICI; pass measured
    ``latency_s``/``bandwidth_bytes_per_s`` for other interconnects, or
    a :class:`LinkParams` via ``link`` (e.g. ``plan.link`` from the
    measured autotuner) which overrides both.
    """
    if link is not None:
        latency_s = link.latency_s
        bandwidth_bytes_per_s = link.bandwidth_bytes_per_s
    if total_bytes <= 0:
        return min_bucket
    if axis_size <= 1:
        return max(min_bucket, int(total_bytes))
    frac = 2.0 * (axis_size - 1) / axis_size
    b_star = (total_bytes * latency_s * bandwidth_bytes_per_s / frac) ** 0.5
    return int(min(max(b_star, min_bucket), total_bytes))


def choose_prefetch_depth(host_time_s: float, device_time_s: float,
                          jitter: float = 0.5, min_depth: int = 2,
                          max_depth: int = 8) -> int:
    """Slot count for the prefetch ring (``PrefetchIterator(depth=...)``)
    from the measured host-assembly vs device-step times (the updater's
    ``main/host_time`` / ``main/device_time``, or the ``updater/*``
    profiler rows).

    The pipeline model: one background worker assembles windows at rate
    ``1/h`` while the device consumes at ``1/d``.  With ``rho = h/d``:

    - **device-bound** (``rho <= 1``): the worker outruns the consumer,
      so two slots — one being consumed, one staged — already hide ALL
      host work; extra depth only adds host memory.  Depth stays at
      ``min_depth`` (= 2, classic double buffering).
    - **host-bound** (``rho > 1``): no depth makes a single worker
      faster — the pipe throughput is pinned at ``1/h`` — but depth
      absorbs *burstiness*: a slow pull (page-cache miss, decode spike)
      up to ``depth - 1`` windows long passes without stalling the
      device, as long as the mean keeps up.  Budget ``ceil(rho)`` slots
      of steady-state lag plus ``jitter`` × that for variance, clamped
      to ``max_depth`` (each slot pins a full device-put batch).

    Returns an int in ``[min_depth, max_depth]``.
    """
    if host_time_s < 0 or device_time_s < 0:
        raise ValueError(
            f"need host_time_s >= 0 and device_time_s >= 0, got "
            f"{host_time_s} / {device_time_s}")
    if min_depth < 1 or max_depth < min_depth:
        raise ValueError(f"bad depth bounds [{min_depth}, {max_depth}]")
    if device_time_s == 0:
        # a zero device time is real profiler output, not an error: a
        # fully-overlapped pipeline measures ~0 exposed device wait, and
        # a first-iteration probe may not have retired anything yet.
        # host == 0 too -> no evidence either way, classic double
        # buffering; host > 0 -> the host-bound limit (rho -> inf).
        return min_depth if host_time_s == 0 else max_depth
    rho = host_time_s / device_time_s
    if rho <= 1.0 + 1e-9:          # tolerance: fp noise must not flip regimes
        return min_depth
    depth = -(-int(rho * (1.0 + jitter) * 1000) // 1000)  # ceil, fp-safe
    return max(min_depth, min(depth + 1, max_depth))


def choose_gather_prefetch_depth(
    layer_bytes: float,
    axis_size: int,
    layer_compute_s: float,
    latency_s: float = _DEFAULT_LATENCY_S,
    bandwidth_bytes_per_s: float = _DEFAULT_BANDWIDTH,
    link: Optional[LinkParams] = None,
    min_window: int = 1,
    max_window: int = 4,
) -> int:
    """ZeRO-3 layer-gather prefetch window from the latency-bandwidth
    model (``ShardedState.auto_window`` / ``LayerGatherStream(window=)``).

    A window of ``W`` means layer ``i``'s all-gather is issued ``W``
    layers ahead, so it has ``W`` layers' compute to hide behind.  One
    gather of a layer's ``s = layer_bytes`` params over ``n`` devices
    costs ``t_g = alpha + s (n-1) / (n * beta)`` on the ring; the
    smallest window that fully hides it is ``1 + ceil(t_g / t_c)`` for
    per-layer compute ``t_c`` (the ``+1`` is the layer currently being
    consumed — classic double buffering at ``t_g <= t_c``).  Clamped to
    ``[min_window, max_window]``: each extra slot keeps one more layer's
    FULL params resident, which is exactly the memory ZeRO-3 exists to
    shed.  Defaults model ICI; a :class:`LinkParams` via ``link`` (e.g.
    ``LinkParams(**plan.link)`` from the measured autotuner) overrides
    both scalars.
    """
    if link is not None:
        latency_s = link.latency_s
        bandwidth_bytes_per_s = link.bandwidth_bytes_per_s
    if layer_bytes < 0 or layer_compute_s < 0:
        raise ValueError(
            f"need layer_bytes >= 0 and layer_compute_s >= 0, got "
            f"{layer_bytes} / {layer_compute_s}")
    if min_window < 1 or max_window < min_window:
        raise ValueError(f"bad window bounds [{min_window}, {max_window}]")
    if axis_size <= 1:
        return min_window          # nothing to gather, nothing to hide
    t_g = latency_s + layer_bytes * (axis_size - 1) / (
        axis_size * bandwidth_bytes_per_s)
    if layer_compute_s == 0:
        # no compute measured yet (first-step probe): nothing to hide
        # behind, so take the deepest window the memory budget allows.
        return max_window
    depth = 1 + math.ceil(t_g / layer_compute_s - 1e-9)
    return max(min_window, min(depth, max_window))


def choose_accum_steps(
    grad_bytes: float,
    axis_size: int,
    microbatch_time_s: float,
    latency_s: float = _DEFAULT_LATENCY_S,
    bandwidth_bytes_per_s: float = _DEFAULT_BANDWIDTH,
    bucket_bytes: Optional[int] = None,
    comm_fraction: float = 0.05,
    max_accum: int = 64,
    link: Optional[LinkParams] = None,
) -> int:
    """Accumulation window ``M`` for ``StandardUpdater(accum_steps=M)``
    from the bytes/step-vs-interconnect model.

    With window-fused accumulation the gradient exchange fires once per
    ``M`` microbatches, so its amortised per-microbatch cost is
    ``T_ex / M`` where (ring formula, fused buckets)

        ``T_ex = ceil(G/b) * alpha + 2 G (n-1) / (n * beta)``

    (``G`` gradient bytes, ``b`` bucket size, ``alpha`` launch latency,
    ``beta`` per-device ring bandwidth, ``n`` axis size).  This picks
    the smallest ``M`` that pushes the amortised exchange below
    ``comm_fraction`` of the measured microbatch compute time
    (``main/step_time`` with ``accum_steps=1``, or an estimate), clamped
    to ``[1, max_accum]`` — past that point accumulation buys
    vanishing wall-clock and only delays parameter updates (the
    statistical large-batch trade-off is the user's call; see
    ``docs/PIPELINE.md``).

    Returns 1 when the axis doesn't span multiple members (nothing to
    amortise) or there are no gradient bytes.  ``link`` (a
    :class:`LinkParams`, e.g. from the measured autotuner) overrides
    ``latency_s``/``bandwidth_bytes_per_s`` with measured constants.
    """
    if link is not None:
        latency_s = link.latency_s
        bandwidth_bytes_per_s = link.bandwidth_bytes_per_s
    if grad_bytes < 0:
        raise ValueError(f"grad_bytes {grad_bytes} must be >= 0")
    if microbatch_time_s <= 0:
        raise ValueError(
            f"microbatch_time_s {microbatch_time_s} must be > 0")
    if comm_fraction <= 0:
        raise ValueError(f"comm_fraction {comm_fraction} must be > 0")
    if max_accum < 1:
        raise ValueError(f"max_accum {max_accum} must be >= 1")
    if axis_size <= 1 or grad_bytes == 0:
        return 1
    b = bucket_bytes or choose_bucket_bytes(
        grad_bytes, axis_size, latency_s, bandwidth_bytes_per_s)
    n_buckets = fused_collective_budget(int(grad_bytes), int(b))
    t_ex = n_buckets * latency_s + 2.0 * grad_bytes * (axis_size - 1) / (
        axis_size * bandwidth_bytes_per_s)
    m = math.ceil(t_ex / (comm_fraction * microbatch_time_s))
    return max(1, min(m, max_accum))


def fused_collective_budget(total_bytes: int, bucket_bytes: int,
                            n_dtype_groups: int = 1) -> int:
    """Upper bound on collectives the fused lowering may emit for
    ``total_bytes`` of gradients in ``n_dtype_groups`` dtype groups:
    each group independently emits ``ceil(group_bytes/bucket)``, and
    splitting ``total_bytes`` over ``g`` groups adds at most ``g - 1``
    ragged buckets over the single-group ``ceil(total/bucket)``."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes {bucket_bytes} must be positive")
    return -(-int(total_bytes) // int(bucket_bytes)) \
        + max(0, n_dtype_groups - 1)


def assert_fused_collectives(stats: Dict[str, "CollectiveStats"],
                             total_bytes: int, bucket_bytes: int,
                             n_dtype_groups: int = 1,
                             kinds=("all-reduce",)) -> int:
    """Assert a compiled program's collective stats respect the fused
    budget: across ``kinds``, at most
    :func:`fused_collective_budget` call sites (the per-leaf baseline
    emits one per leaf — hundreds for a transformer grad tree).
    Returns the observed count."""
    budget = fused_collective_budget(total_bytes, bucket_bytes,
                                     n_dtype_groups)
    count = sum(stats[k].count for k in kinds if k in stats)
    if count > budget:
        raise AssertionError(
            f"fused lowering emitted {count} {'+'.join(kinds)} "
            f"collectives, budget is {budget} "
            f"(= ceil({total_bytes}/{bucket_bytes}) + "
            f"{max(0, n_dtype_groups - 1)} ragged group buckets)")
    return count


def assert_accum_collectives(
    stats: Dict[str, "CollectiveStats"],
    total_bytes: int,
    bucket_bytes: int,
    n_dtype_groups: int = 1,
    kinds=("all-reduce", "reduce-scatter", "all-gather"),
    extra: int = 1,
) -> int:
    """Assert a compiled accumulation step exchanges gradients ONCE per
    window — the M→1 proof for ``StandardUpdater(accum_steps=M)``.

    Two conditions, read off :func:`collective_stats` of the compiled
    steady-state step:

    - **no looped exchange**: zero ``kinds`` call sites inside a
      ``while`` body.  The microbatch scan runs M trips per window; a
      collective there fires M times — exactly the per-microbatch
      regime accumulation exists to retire.
    - **window budget**: total ``kinds`` call sites (all top-level, by
      the first condition, hence once per window) stay within
      :func:`fused_collective_budget` plus ``extra`` — ``extra``
      defaults to 1 for the scalar loss mean the updater reports
      (4 wire bytes; not a gradient exchange).

    Returns the observed per-window count.  Apply to a
    ``steps_per_execution == 1`` program: an outer fused-step scan
    legitimately wraps the per-window exchange in a while body of its
    own, which this check would (rightly, conservatively) reject.
    """
    looped = sum(stats[k].looped for k in kinds if k in stats)
    if looped:
        raise AssertionError(
            f"accumulation scan still exchanges per microbatch: "
            f"{looped} {'+'.join(kinds)} call site(s) inside a while "
            f"body (want 0 — the window-end exchange must sit outside "
            f"the scan)")
    budget = fused_collective_budget(total_bytes, bucket_bytes,
                                     n_dtype_groups) + extra
    count = sum(stats[k].count for k in kinds if k in stats)
    if count > budget:
        raise AssertionError(
            f"accumulation window emitted {count} {'+'.join(kinds)} "
            f"collectives, budget is {budget} "
            f"(= ceil({total_bytes}/{bucket_bytes}) + "
            f"{max(0, n_dtype_groups - 1)} ragged group buckets + "
            f"{extra} extra)")
    return count


# backward compute markers for the overlap proof: the matmul-shaped ops
# a training step's forward/backward is made of.  Elementwise optimiser
# math lowers to fusions without any of these, so "the last dot" is a
# faithful end-of-backward marker in the schedule.
_COMPUTE_RE = re.compile(
    r"=\s*[^ ]+\s+(?:dot|convolution)\(|"
    r"custom-call.*(?:matmul|convolution)")


def assert_overlap_collectives(
    compiled,
    kinds=("all-reduce", "reduce-scatter", "all-gather"),
    min_bytes: int = 256,
    min_frac: float = 0.5,
) -> dict:
    """Prove, from the compiled schedule, that the gradient exchange
    runs UNDER the backward pass — the overlap analogue of
    :func:`assert_fused_collectives` / :func:`assert_accum_collectives`.

    XLA prints each computation of an optimised module in execution-
    schedule order, so position is evidence: an exchange collective
    scheduled BEFORE the computation's last matmul-shaped op
    (``dot``/``convolution``/a matmul custom-call) starts while
    backward compute still remains — wire time that can hide.  The
    window-end lowerings place every exchange collective after the
    last backward op; the overlap lowering interleaves them.

    Args:
      compiled: a ``jax.stages.Compiled`` training step (apply to a
        ``steps_per_execution == 1`` program; under an outer fused-step
        scan the while body is the computation measured).
      kinds: collective kinds that constitute the exchange.
      min_bytes: ignore call sites smaller than this (the reported
        scalar loss pmean is 4 bytes and always sits at the window end
        by construction — it is not a gradient exchange).
      min_frac: minimum fraction of exchange collectives that must
        start inside the backward region.

    Returns ``{"inside": n, "total": n, "frac": f, "async_depth": d}``
    (``async_depth`` summed over ``kinds`` — nonzero only on backends
    that emit async start/done pairs).  Raises ``AssertionError`` when
    fewer than ``min_frac`` of the exchange collectives start inside
    the backward region, or when no exchange collective is found at
    all (nothing to prove).
    """
    kinds = tuple(kinds)
    inside = total = 0
    any_compute = False
    for text in _hlo_texts(compiled):
        for comp_name, lines in _split_computations(text).items():
            coll_idx = []
            last_compute = None
            for i, line in enumerate(lines):
                if _COMPUTE_RE.search(line):
                    last_compute = i
                    any_compute = True
                    continue
                m = _INSTR_RE.search(line)
                if not m or m.group(2) not in kinds:
                    continue
                if _group_size(line) == 1:
                    continue
                if _shape_bytes(m.group(1),
                                is_start=bool(m.group(3))) < min_bytes:
                    continue
                coll_idx.append(i)
            total += len(coll_idx)
            # a collective in a compute-free computation counts as
            # OUTSIDE: the accum window-end shape puts every backward
            # dot inside the scan body and the exchange in the entry —
            # maximal non-overlap, not missing evidence
            if last_compute is not None:
                inside += sum(1 for i in coll_idx if i < last_compute)
    if total == 0 or not any_compute:
        missing = ("no matmul-shaped backward op" if total
                   else f"no {'+'.join(kinds)} exchange collective of "
                        f">= {min_bytes} bytes")
        raise AssertionError(
            f"nothing to prove overlap on: {missing} in the compiled "
            f"program (wrong program, or min_bytes too high)")
    stats = collective_stats(compiled)
    async_depth = sum(stats[k].async_depth for k in kinds if k in stats)
    frac = inside / total
    if frac < min_frac:
        raise AssertionError(
            f"exchange collectives cluster after the backward pass: "
            f"{inside}/{total} ({frac:.0%}) start inside the backward "
            f"region, need >= {min_frac:.0%} — the lowering is not "
            f"overlapping (window-end join, or the scheduler sank the "
            f"collectives)")
    return {"inside": inside, "total": total, "frac": frac,
            "async_depth": async_depth}


def overlap_exposed_time(
    bucket_wire_bytes,
    axis_size: int,
    t_bwd_s: float,
    latency_s: float = _DEFAULT_LATENCY_S,
    bandwidth_bytes_per_s: float = _DEFAULT_BANDWIDTH,
    modes=None,
    launches_per_bucket: int = 2,
    link: Optional[LinkParams] = None,
) -> float:
    """EXPOSED wire seconds of a backward-overlapped exchange — the
    overlap-aware cost model behind the schedule search.

    Buckets arrive in stream order (index 0 = the reverse-layer bucket
    whose gradients the backward produces FIRST).  Modeling gradient
    production as uniform in bytes over ``t_bwd_s``, eager bucket ``i``
    becomes ready at ``t_bwd_s × (cumulative bytes through i) /
    (total bytes)``; a ``deferred`` bucket is ready only when the
    backward finishes.  The wire serialises buckets (one fabric): each
    starts at ``max(ready, wire_free)`` and holds the wire for

        ``t_wire = launches_per_bucket · α + 2·b·(n-1)/(n·β)``

    (ring all-reduce bytes; reduce-scatter→all-gather moves the same
    total).  The exposed cost is ``max(0, finish − t_bwd_s)`` — per
    bucket, wire time is only paid where ``T_wire`` exceeds the
    remaining backward compute, which is the ``max(0, T_wire −
    T_bwd_remaining)`` shape the window-end model lacks.  A window-end
    exchange is the degenerate all-``deferred`` schedule: exposed =
    full ``T_ex``.

    Args:
      bucket_wire_bytes: per-bucket wire byte counts, stream order.
      axis_size: reduction-axis size ``n``.
      t_bwd_s: backward wall time the stream can hide under.
      modes: per-bucket ``"eager"``/``"deferred"`` (default all eager).
      launches_per_bucket: collective launches per bucket — a scalar
        (2 for rs→ag, 1 for a lone all-reduce) or a per-bucket
        sequence, so mixed-``via`` schedules price their launch costs
        truthfully.
      link: measured :class:`LinkParams` override (e.g. ``plan.link``).

    Returns exposed seconds (0.0 = the exchange fully hides).
    """
    if link is not None:
        latency_s = link.latency_s
        bandwidth_bytes_per_s = link.bandwidth_bytes_per_s
    buckets = [float(b) for b in bucket_wire_bytes]
    if not buckets or axis_size <= 1:
        return 0.0
    if t_bwd_s < 0:
        raise ValueError(f"t_bwd_s {t_bwd_s} must be >= 0")
    if modes is None:
        modes = ["eager"] * len(buckets)
    if len(modes) != len(buckets):
        raise ValueError(
            f"{len(modes)} modes for {len(buckets)} buckets")
    if isinstance(launches_per_bucket, (int, float)):
        launches = [float(launches_per_bucket)] * len(buckets)
    else:
        launches = [float(x) for x in launches_per_bucket]
        if len(launches) != len(buckets):
            raise ValueError(
                f"{len(launches)} launch counts for {len(buckets)} "
                f"buckets")
    total = sum(buckets) or 1.0
    frac = 2.0 * (axis_size - 1) / axis_size
    cum = 0.0
    order = []                  # (ready_s, t_wire_s), stream order
    deferred = []
    for b, mode, k in zip(buckets, modes, launches):
        cum += b
        t_wire = k * latency_s + b * frac / bandwidth_bytes_per_s
        if mode == "deferred":
            deferred.append((t_bwd_s, t_wire))
        elif mode == "eager":
            order.append((t_bwd_s * cum / total, t_wire))
        else:
            raise ValueError(f"unknown bucket mode {mode!r}")
    wire_free = 0.0
    for ready, t_wire in order + deferred:
        wire_free = max(ready, wire_free) + t_wire
    return max(0.0, wire_free - t_bwd_s)


def axis_collective_report(build_step, axes_sizes, n_devices=8):
    """Per-mesh-axis collective volume for one training step.

    Args:
      build_step: ``build_step(mesh_axes: dict) -> (fn, args)`` — builds
        the jitted step for a mesh with the given axis sizes (every
        other axis 1) and returns it unlowered with example args.
      axes_sizes: e.g. ``{"data": 8, "model": 8}`` — each axis is
        activated ALONE at its size (the single-active-axis trick).
      n_devices: virtual devices available.

    Returns ``{axis: {"stats": {kind: CollectiveStats}, "axis_size": n,
    "wire_bytes_per_device": float}}``.
    """
    report = {}
    for axis, n in axes_sizes.items():
        if n > n_devices:
            raise ValueError(f"{axis}={n} exceeds {n_devices} devices")
        fn, args = build_step({axis: n})
        compiled = fn.lower(*args).compile()
        stats = collective_stats(compiled)
        report[axis] = {
            "axis_size": n,
            "stats": stats,
            "wire_bytes_per_device": sum(
                s.wire_bytes(n) for s in stats.values()),
        }
    return report
