"""SLO burn-rate alerting over the metrics registry.

The metrics layer (:mod:`chainermn_tpu.utils.metrics`) makes latency
and failure *distributions* readable; the SLO report scores a run
after the fact.  Nothing WATCHES those distributions while the job
runs: an overload that torches the error budget is only visible when
an operator reads a report.  This module is the watching half —
multi-window **burn-rate** rules in the SRE-workbook formulation,
evaluated over the same registry counters/histograms the dashboards
scrape, with the same measured-not-modeled stance as the autotuner:
alerts fire off observed ratios, never off a capacity model.

**Burn rate.**  An SLO grants an error budget: ``budget`` is the
allowed bad fraction (0.001 = 99.9%).  Over a trailing window, the
burn rate is ``(bad / total) / budget`` — 1.0 spends the budget
exactly at its sustainable pace, 14.4 exhausts a 30-day budget in 2
days.  A rule fires when BOTH windows of any configured
``(long_s, short_s, factor)`` pair exceed ``factor``: the long window
proves the burn is material, the short window proves it is STILL
happening (so alerts auto-resolve quickly once the cause stops —
the classic multi-window multi-burn-rate construction).

Two signal shapes:

- :class:`RatioRule` — bad/total from counters (e.g. ``serve/
  shed_total`` + ``serve/timeouts`` over ``serve/submitted``).
- :class:`LatencyRule` — bad = observations ABOVE a latency threshold,
  read from a lattice histogram's buckets (e.g. ``serve/ttft`` above
  500 ms).  The threshold rounds UP to its lattice edge, so the
  bad-count is exact, never interpolated.

:class:`AlertManager` samples rules on :meth:`~AlertManager.tick`
(injectable clock — window math is unit-testable without sleeping),
tracks per-rule firing state, counts transitions into the registry
(``alerts/fired`` / ``alerts/resolved`` counters, ``alerts/firing``
gauge), appends each transition to an alert log (atomic per line —
:func:`~chainermn_tpu.utils.metrics.append_jsonl`), and exposes:

- :meth:`~AlertManager.protective` — the advisory hint an
  :class:`~chainermn_tpu.serving.admission.AdmissionController`
  consumes (``alert_advisor=``) to shed below-tier traffic
  ``"overload"`` while the budget burns;
- :meth:`~AlertManager.state` — the JSON block ``/statusz`` serves and
  the :class:`~chainermn_tpu.extensions.TrainingWatchdog` embeds in
  stall reports (:func:`install` / :func:`get_installed` is the
  no-argument discovery point those consumers use).

Pure stdlib, importable without jax, and quiet by construction: a
broken rule degrades to an ``"error"`` state, a disabled registry
reads as no-evidence (burn ``None``), and nothing here ever raises
into the serving/training loop.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

from chainermn_tpu.utils.metrics import (
    append_jsonl,
    bucket_index,
    get_registry,
)

__all__ = [
    "AlertManager",
    "BurnRateRule",
    "DEFAULT_WINDOWS",
    "LatencyRule",
    "RatioRule",
    "get_installed",
    "install",
]

#: The SRE-workbook page/ticket pair: a 1h/5m window firing at 14.4×
#: burn (2-day budget exhaustion) and a 6h/30m window at 6× (5-day).
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (3600.0, 300.0, 14.4),
    (21600.0, 1800.0, 6.0),
)


def _names(spec: Union[str, Sequence[str]]) -> Tuple[str, ...]:
    return (spec,) if isinstance(spec, str) else tuple(spec)


class BurnRateRule:
    """Base rule: identity, budget, windows, the protective flag.

    Args:
      name: rule identity (alert log / statusz / transition key).
      budget: the allowed bad fraction of the SLO (0 < budget < 1);
        burn rate = observed bad fraction / budget.
      windows: ``(long_s, short_s, factor)`` triples; the rule fires
        while ANY triple has BOTH trailing windows burning at >=
        ``factor``.
      protect: whether this rule's firing should count toward
        :meth:`AlertManager.protective` (the admission advisory).
    """

    def __init__(self, name: str, *, budget: float,
                 windows: Sequence[Tuple[float, float, float]]
                 = DEFAULT_WINDOWS,
                 protect: bool = True):
        if not 0.0 < budget < 1.0:
            raise ValueError(f"budget={budget} not in (0, 1)")
        wins = tuple((float(l), float(s), float(f))
                     for l, s, f in windows)
        if not wins:
            raise ValueError("windows must not be empty")
        for l, s, f in wins:
            if not 0 < s <= l:
                raise ValueError(
                    f"window pair ({l}, {s}): short must satisfy "
                    "0 < short <= long")
            if f <= 0:
                raise ValueError(f"burn factor {f} must be > 0")
        self.name = str(name)
        self.budget = float(budget)
        self.windows = wins
        self.protect = bool(protect)

    def read(self, registry) -> Tuple[float, float]:
        """Cumulative ``(bad, total)`` as of now (both monotonic —
        the manager differences consecutive reads)."""
        raise NotImplementedError

    def describe(self) -> dict:
        return {"budget": self.budget,
                "windows": [list(w) for w in self.windows],
                "protect": self.protect}


class RatioRule(BurnRateRule):
    """Bad fraction from counters: ``bad`` / ``total`` name(s), each a
    counter or a list of counters summed (e.g. ``bad=["serve/
    shed_total", "serve/timeouts"], total="serve/submitted"``)."""

    def __init__(self, name: str, *, bad: Union[str, Sequence[str]],
                 total: Union[str, Sequence[str]], budget: float,
                 **kwargs):
        super().__init__(name, budget=budget, **kwargs)
        self.bad = _names(bad)
        self.total = _names(total)

    def read(self, registry) -> Tuple[float, float]:
        def total(names):
            return float(sum(registry.counter(n).value
                             for n in names))

        return total(self.bad), total(self.total)

    def describe(self) -> dict:
        return {**super().describe(), "kind": "ratio",
                "bad": list(self.bad), "total": list(self.total)}


class LatencyRule(BurnRateRule):
    """Bad fraction from a lattice histogram: observations ABOVE
    ``above`` seconds are bad (``above`` rounds UP to the edge of the
    lattice bucket containing it — the count of strictly-higher
    buckets is exact, so no interpolation enters an alerting
    decision), total is the histogram's count."""

    def __init__(self, name: str, *, histogram: str, above: float,
                 budget: float, **kwargs):
        super().__init__(name, budget=budget, **kwargs)
        if above <= 0:
            raise ValueError(f"above={above} must be > 0 seconds")
        self.histogram = str(histogram)
        self.above = float(above)
        self._edge_idx = bucket_index(self.above)

    def read(self, registry) -> Tuple[float, float]:
        h = registry.histogram(self.histogram)
        above = getattr(h, "count_above", None)
        if above is None:           # a foreign/legacy histogram object
            counts = h.bucket_counts()
            bad = sum(c for i, c in counts.items()
                      if i > self._edge_idx)
        else:
            bad = above(self._edge_idx)
        return float(bad), float(h.count)

    def describe(self) -> dict:
        return {**super().describe(), "kind": "latency",
                "histogram": self.histogram, "above": self.above}


class AlertManager:
    """Evaluate burn-rate rules over the registry and track alert
    state.

    Args:
      rules: the :class:`BurnRateRule`\\ s to watch (unique names).
      registry: metrics registry to read AND count transitions into
        (default the process-global one, resolved per tick so
        ``set_registry`` swaps are honored).
      clock: the time source for window math (default
        ``time.monotonic``).  Injectable: the unit tests drive hours
        of window history in microseconds, and the overload drill
        replays a recorded trace on a fake clock.
      log_path: append one JSON line per alert TRANSITION (fire and
        resolve) — atomic per line, never a torn tail.
      min_total: evidence floor — a window whose total delta is below
        this reports burn ``None`` (no traffic is not an outage).
      min_interval: evaluation rate limit in clock seconds (default 0
        = evaluate every tick).  The burn windows are minutes-to-hours
        long, so rule evaluation gains nothing from sub-second
        cadence; with a ``min_interval``, :meth:`tick` called from a
        tight loop (every serving scheduler step, say) is one clock
        read + compare until the interval elapses — the
        evaluate-on-an-interval shape every rule engine (Prometheus
        included) uses.

    Drive it by calling :meth:`tick` on any cadence (a trainer
    extension trigger, the serving loop, a monitor thread); each tick
    samples every rule's cumulative ``(bad, total)``, prunes history
    past the longest window, and recomputes firing state.
    """

    def __init__(self, rules: Sequence[BurnRateRule], *,
                 registry=None, clock=time.monotonic,
                 log_path: Optional[str] = None, min_total: int = 1,
                 min_interval: float = 0.0):
        rules = tuple(rules)
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        if min_total < 1:
            raise ValueError(f"min_total={min_total} must be >= 1")
        if min_interval < 0:
            raise ValueError(
                f"min_interval={min_interval} must be >= 0")
        self.rules = rules
        self.registry = registry
        self.clock = clock
        self.log_path = log_path
        self.min_total = int(min_total)
        self.min_interval = float(min_interval)
        self._last_eval: Optional[float] = None
        self._samples: Dict[str, collections.deque] = {
            r.name: collections.deque() for r in rules}
        # sample-retention resolution floor: a new tick REPLACES the
        # newest sample unless at least shortest_window/64 clock
        # seconds have passed, so the deque holds O(longest/gap)
        # entries however fast the caller ticks (a 100 Hz scheduler
        # loop over a 6 h window would otherwise retain millions) —
        # window baselines shift by < the gap, far inside burn noise
        self._min_gap: Dict[str, float] = {
            r.name: min(s for _l, s, _f in r.windows) / 64.0
            for r in rules}
        # the last APPEND time (replacements don't move it — the gap
        # must accumulate against the anchor, or a fast ticker would
        # replace the same sample forever and retain no history)
        self._anchor: Dict[str, Optional[float]] = {
            r.name: None for r in rules}
        self._state: Dict[str, str] = {r.name: "ok" for r in rules}
        # the firing flag survives read errors: an evaluation error
        # must neither resolve an active alert (protective shedding
        # would silently drop mid-overload) nor double-count its
        # eventual transitions
        self._firing: Dict[str, bool] = {r.name: False for r in rules}
        self._since: Dict[str, Optional[float]] = {
            r.name: None for r in rules}
        self._burn: Dict[str, dict] = {r.name: {} for r in rules}
        self._detail: Dict[str, str] = {}
        self.fired = 0
        self.resolved = 0
        self.ticks = 0
        self.evals = 0

    # -- evaluation ---------------------------------------------------- #

    @staticmethod
    def _window_burn(dq, now: float, window: float, budget: float,
                     min_total: int) -> Optional[float]:
        """Burn rate over the trailing ``window``: delta bad fraction
        vs the newest sample at or before ``now - window`` (the
        window's baseline), divided by the budget.  ``None`` while the
        evidence is thinner than ``min_total`` observations — or while
        the history does not yet REACH back a full window: a partial
        long window would degenerate to the short window and let a
        startup blip fire the sustained-burn rule (and its protective
        shedding) off seconds of data, defeating the multi-window
        construction."""
        if not dq:
            return None
        t_now, bad_now, total_now = dq[-1]
        base = None
        for t, bad, total in dq:        # oldest-first
            if t <= now - window:
                base = (t, bad, total)
            else:
                break
        if base is None:
            return None                 # window not yet covered
        d_total = total_now - base[2]
        if d_total < min_total:
            return None
        d_bad = bad_now - base[1]
        return (d_bad / d_total) / budget

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Sample every rule and update alert state; returns this
        tick's TRANSITIONS (fired/resolved events, empty most ticks).
        Never raises: a broken rule parks in state ``"error"`` until
        it reads again."""
        if now is None:
            now = self.clock()
        now = float(now)
        self.ticks += 1
        if self._last_eval is not None and self.min_interval > 0.0 \
                and now - self._last_eval < self.min_interval:
            return []               # rate-limited: nothing re-read
        self._last_eval = now
        self.evals += 1
        reg = self.registry if self.registry is not None \
            else get_registry()
        events: List[dict] = []
        for rule in self.rules:
            prev_firing = self._firing[rule.name]
            try:
                bad, total = rule.read(reg)
            except Exception as err:    # noqa: BLE001 — never raise out
                self._state[rule.name] = "error"
                self._detail[rule.name] = \
                    f"{type(err).__name__}: {err}"
                continue            # firing flag HELD until it reads
            self._detail.pop(rule.name, None)
            dq = self._samples[rule.name]
            anchor = self._anchor[rule.name]
            if dq and anchor is not None \
                    and now - anchor < self._min_gap[rule.name]:
                dq[-1] = (now, float(bad), float(total))
            else:
                dq.append((now, float(bad), float(total)))
                self._anchor[rule.name] = now
            longest = max(w[0] for w in rule.windows)
            # keep ONE sample at/behind the longest window's baseline
            while len(dq) >= 2 and dq[1][0] <= now - longest:
                dq.popleft()
            burn: Dict[str, Optional[float]] = {}
            firing = False
            for long_s, short_s, factor in rule.windows:
                bl = self._window_burn(dq, now, long_s, rule.budget,
                                       self.min_total)
                bs = self._window_burn(dq, now, short_s, rule.budget,
                                       self.min_total)
                burn[f"{long_s:g}s"] = bl
                burn[f"{short_s:g}s"] = bs
                if bl is not None and bs is not None \
                        and bl >= factor and bs >= factor:
                    firing = True
            self._burn[rule.name] = burn
            self._state[rule.name] = "firing" if firing else "ok"
            if firing == prev_firing:
                continue
            self._firing[rule.name] = firing
            self._since[rule.name] = now if firing else None
            if firing:
                self.fired += 1
            else:
                self.resolved += 1
            event = {
                "ts": time.time(),
                "t": now,
                "rule": rule.name,
                "transition": "fired" if firing else "resolved",
                "burn": burn,
                "bad": bad,
                "total": total,
                **rule.describe(),
            }
            events.append(event)
            reg.inc("alerts/fired" if firing else "alerts/resolved")
            if self.log_path is not None:
                try:
                    append_jsonl(self.log_path, event)
                except OSError:
                    pass    # alerting must never kill the job
        reg.set("alerts/firing", len(self.firing()))
        return events

    # -- read surface -------------------------------------------------- #

    def firing(self) -> Tuple[str, ...]:
        """Names of the rules currently firing (a rule whose read is
        erroring HOLDS its last evaluated firing state — an evaluation
        error is not evidence the overload stopped)."""
        return tuple(name for name, f in self._firing.items() if f)

    def protective(self) -> bool:
        """The admission advisory: True while any ``protect=True``
        rule fires (the ``AdmissionController.alert_advisor``
        contract)."""
        by_name = {r.name: r for r in self.rules}
        return any(by_name[name].protect for name in self.firing())

    def state(self) -> dict:
        """The full JSON-safe state block (``/statusz`` ``alerts``
        section; embedded in watchdog stall reports)."""
        rules = {}
        for rule in self.rules:
            rules[rule.name] = {
                "state": self._state[rule.name],
                "since": self._since[rule.name],
                "burn": self._burn[rule.name],
                **rule.describe(),
            }
            if rule.name in self._detail:
                rules[rule.name]["detail"] = self._detail[rule.name]
        return {
            "ticks": self.ticks,
            "evals": self.evals,
            "fired": self.fired,
            "resolved": self.resolved,
            "firing": list(self.firing()),
            "protective": self.protective(),
            "rules": rules,
        }


# ---------------------------------------------------------------------- #
# process-global discovery (the watchdog / statusz hookup)
# ---------------------------------------------------------------------- #

_INSTALLED: Optional[AlertManager] = None


def install(manager: Optional[AlertManager]) -> Optional[AlertManager]:
    """Install ``manager`` as the process's discoverable alert manager
    (``None`` uninstalls); returns the previous one.  The watchdog
    embeds the installed manager's :meth:`~AlertManager.state` in
    stall reports, and ``statusz`` serves it when not given one
    explicitly — neither takes a constructor argument hostage."""
    global _INSTALLED
    prev = _INSTALLED
    _INSTALLED = manager
    return prev


def get_installed() -> Optional[AlertManager]:
    return _INSTALLED
