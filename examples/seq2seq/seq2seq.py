"""Seq2seq NMT data-parallel training — analogue of the reference's
``examples/seq2seq/seq2seq.py`` (mpiexec-launched encoder-decoder NMT;
unverified — mount empty, see SURVEY.md).

The reference trained WMT en↔fr with ragged minibatches; its distributed
point was that *variable-length* gradients still allreduce. Zero-egress
environment → a synthetic "reverse translation" task (target = reversed
source) with genuinely variable lengths; the converter pads each batch to
ONE static shape so the whole run is a single compiled program (the
TPU-first answer to raggedness — see models/seq2seq.py docstring).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_dataset(n=2048, vocab=50, min_len=3, max_len=16, seed=0):
    """(src, tgt) int32 pairs, tgt = reversed(src) + EOS, variable length."""
    from chainermn_tpu.models.seq2seq import EOS

    rng = np.random.RandomState(seed)
    pairs = []
    for _ in range(n):
        length = rng.randint(min_len, max_len + 1)
        src = rng.randint(3, vocab, size=length).astype(np.int32)
        tgt = np.concatenate([src[::-1], [EOS]]).astype(np.int32)
        pairs.append((src, tgt))
    return pairs[: n * 9 // 10], pairs[n * 9 // 10:]


def make_converter(max_src, max_tgt):
    """Pad a ragged batch to ONE static shape (jit compiles once)."""
    from chainermn_tpu.models.seq2seq import PAD

    def convert(batch):
        srcs, tgts = zip(*batch)
        src = np.full((len(batch), max_src), PAD, np.int32)
        tgt = np.full((len(batch), max_tgt), PAD, np.int32)
        for i, (s, t) in enumerate(zip(srcs, tgts)):
            src[i, : len(s)] = s
            tgt[i, : len(t)] = t
        return src, tgt

    return convert


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--communicator", default="tpu_xla")
    p.add_argument("--batchsize", type=int, default=64)
    p.add_argument("--epoch", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--unit", type=int, default=128)
    p.add_argument("--platform", default=None)
    p.add_argument("--out", default="result")
    args = p.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import jax
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models.seq2seq import (
        Seq2seqConfig, init_seq2seq, seq2seq_loss, seq2seq_translate,
    )

    comm = cmn.create_communicator(args.communicator)
    if comm.rank == 0:
        print(f"world: {comm.size} devices, {comm.inter_size} processes")

    VOCAB, MAX_SRC, MAX_TGT = 50, 16, 17
    train, test = make_dataset(vocab=VOCAB, max_len=MAX_SRC)
    train = cmn.scatter_dataset(train, comm, shuffle=True, seed=0)
    test = cmn.scatter_dataset(test, comm)
    convert = make_converter(MAX_SRC, MAX_TGT)

    cfg = Seq2seqConfig(
        src_vocab=VOCAB, tgt_vocab=VOCAB,
        d_embed=args.unit, d_hidden=args.unit, n_layers=2)
    params = init_seq2seq(jax.random.PRNGKey(0), cfg)
    opt = cmn.create_multi_node_optimizer(optax.adam(args.lr), comm)

    def loss_fn(params, src, tgt):
        return seq2seq_loss(cfg, params, src, tgt)

    train_it = cmn.SerialIterator(train, args.batchsize, shuffle=True, seed=1)
    test_it = cmn.SerialIterator(test, args.batchsize, repeat=False)

    updater = cmn.StandardUpdater(
        train_it, opt, loss_fn, params, comm, converter=convert)
    trainer = cmn.Trainer(updater, (args.epoch, "epoch"), out=args.out)

    def metrics_fn(params, src, tgt):
        return {"loss": seq2seq_loss(cfg, params, src, tgt)}

    evaluator = cmn.create_multi_node_evaluator(
        cmn.Evaluator(test_it, metrics_fn, comm, converter=convert), comm)
    trainer.extend(evaluator, trigger=(1, "epoch"))
    log = cmn.LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    if comm.rank == 0:
        trainer.extend(cmn.PrintReport(
            ["epoch", "main/loss", "validation/loss", "elapsed_time"],
            log_report=log))

    trainer.run()

    # greedy-decode a few validation pairs (the reference printed BLEU;
    # for the synthetic reverse task exact-match is the honest metric)
    src, tgt = convert(test[:64])
    out = np.asarray(seq2seq_translate(
        cfg, updater.params, src, max_len=MAX_TGT))
    match = float(np.mean(np.all(out == tgt, axis=1)))
    if comm.rank == 0:
        print(f"greedy exact-match on {len(src)} held-out pairs: {match:.3f}")
    return match


if __name__ == "__main__":
    main()
