"""ImageNet ResNet-50 data-parallel training — analogue of the reference's
``examples/imagenet/train_imagenet.py`` + ``models/resnet50.py``
(mpiexec-launched DP ResNet; unverified — mount empty, see SURVEY.md).

The headline BASELINE.md config: DP ResNet-50, cross-replica BN, bf16
compute (the fp16-allreduce analogue is ``--grad-dtype bfloat16`` on the
multi-node optimizer).  Zero-egress environment → synthetic ImageNet-shaped
data by default; pass ``--train-npz`` with ``x``/``y`` arrays for real
images.  ``--tiny`` shrinks everything for the virtual-pod smoke run.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


class SyntheticImages:
    """Lazy ImageNet-shaped dataset: images are generated per __getitem__
    (a full list would be ~30 GB at 50k × 224²×3 fp32), deterministically
    from the index so every process sees the same logical dataset."""

    def __init__(self, n, image, classes, seed=0):
        self.n, self.image, self.classes = n, image, classes
        self.protos = np.random.RandomState(seed).randn(
            classes, 8).astype("float32")

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        i = int(i)
        c = i % self.classes
        rng = np.random.RandomState(1_000_003 + i)
        # class signal in a low-dim projection so tiny runs can learn it
        x = 0.3 * rng.randn(self.image, self.image, 3).astype("float32")
        x[:8, 0, 0] += self.protos[c]
        return x, np.int32(c)


def make_dataset(n, image, classes, npz=None, seed=0):
    if npz and os.path.exists(npz):
        d = np.load(npz)
        return list(zip(d["x"].astype("float32"), d["y"].astype("int32")))
    return SyntheticImages(n, image, classes, seed)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--communicator", default="tpu_xla")
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet50", "resnet101", "resnet152",
                            "alex", "nin", "vgg16", "googlenet"],
                   help="model architecture (reference --arch parity)")
    p.add_argument("--batchsize", type=int, default=256,
                   help="global batch size")
    p.add_argument("--epoch", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--grad-dtype", default=None,
                   help="allreduce_grad_dtype analogue, e.g. bfloat16")
    p.add_argument("--train-npz", default=None)
    p.add_argument("--loader", default="serial",
                   choices=["serial", "native"],
                   help="'native': the C++ slot-ring prefetch loader "
                        "(chainermn_tpu.native.NativeBatchIterator) "
                        "assembles batches in worker threads ahead of "
                        "the step — the reference's multithreaded "
                        "chainer.iterators analogue; materialises this "
                        "process's shard as field arrays")
    p.add_argument("--platform", default=None)
    p.add_argument("--tiny", action="store_true",
                   help="32px/width-8 model on 512 images (CPU smoke run)")
    p.add_argument("--out", default="result")
    args = p.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import jax
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import (
        ResNetConfig, init_resnet, resnet_apply, softmax_cross_entropy,
        accuracy,
    )

    comm = cmn.create_communicator(args.communicator)
    if comm.rank == 0:
        print(f"world: {comm.size} devices, {comm.inter_size} processes")

    from chainermn_tpu.models import (
        ConvNetConfig, convnet_apply, init_convnet,
    )

    resnet = args.arch.startswith("resnet")
    if args.tiny:
        image, classes, n = 32, 8, 512
        # tiny smoke runs use the GAP head: the reference flatten heads
        # need near-native input sizes (32px collapses to 0 spatial)
        cfg = (ResNetConfig(depth=50, num_classes=classes, width=8,
                            dtype="float32") if resnet
               else ConvNetConfig(arch=args.arch, num_classes=classes,
                                  dtype="float32", head="gap"))
    else:
        image, classes, n = 224, 1000, 50000
        cfg = (ResNetConfig(depth=int(args.arch[6:]), num_classes=classes)
               if resnet
               else ConvNetConfig(arch=args.arch, num_classes=classes,
                                  image_size=image))

    from chainermn_tpu.datasets import SubDataset

    data = make_dataset(n, image, classes, npz=args.train_npz)
    split = len(data) * 9 // 10
    train = SubDataset(data, np.arange(split))
    test = SubDataset(data, np.arange(split, len(data)))
    train = cmn.scatter_dataset(train, comm, shuffle=True, seed=0)
    test = cmn.scatter_dataset(test, comm)

    if resnet:
        params, state = init_resnet(jax.random.PRNGKey(0), cfg)

        def loss_fn(params, state, x, y):
            logits, new_state = resnet_apply(
                cfg, params, state, x, train=True,
                axis_name=comm.axis_name)
            return softmax_cross_entropy(logits, y), new_state
    else:
        params, state = init_convnet(jax.random.PRNGKey(0), cfg), None

        if args.arch == "googlenet":
            # Inception recipe: main + 0.3·(aux_4a + aux_4d)
            def loss_fn(params, x, y):
                logits, a1, a2 = convnet_apply(
                    cfg, params, x, with_aux=True)
                return (softmax_cross_entropy(logits, y)
                        + 0.3 * (softmax_cross_entropy(a1, y)
                                 + softmax_cross_entropy(a2, y)))
        else:
            def loss_fn(params, x, y):
                return softmax_cross_entropy(
                    convnet_apply(cfg, params, x), y)

    opt = cmn.create_multi_node_optimizer(
        optax.sgd(args.lr, momentum=0.9), comm,
        allreduce_grad_dtype=args.grad_dtype)

    converter = None
    if args.loader == "native":
        from chainermn_tpu.native import NativeBatchIterator, \
            native_available

        if comm.rank == 0:
            backend = ("ACTIVE" if native_available()
                       else "unavailable (pure-python fallback)")
            print(f"native loader: C++ backend {backend}")
        # the native loader batches memory-resident field arrays:
        # materialise this process's scattered shard once up front —
        # bounded, because a full-size synthetic shard would be tens of
        # GB (SyntheticImages is lazy for exactly that reason)
        est = len(train) * image * image * 3 * 4
        if est > 4 << 30:
            raise SystemExit(
                f"--loader native materialises the local shard "
                f"(~{est / 2**30:.0f} GB here): use --tiny or point "
                "--train-npz at a real on-disk dataset")
        xs = np.stack([train[i][0] for i in range(len(train))])
        ys = np.asarray([train[i][1] for i in range(len(train))],
                        np.int32)
        train_it = NativeBatchIterator(
            [xs, ys], args.batchsize, shuffle=True, seed=1)
        # COPY out of the loader's recycled slot: the updater may hold
        # several batches at once (steps_per_execution windows) and the
        # C++ prefetch threads reuse slots as soon as they're released
        converter = lambda b: tuple(np.array(a) for a in b)
    else:
        train_it = cmn.SerialIterator(
            train, args.batchsize, shuffle=True, seed=1)
    test_it = cmn.SerialIterator(test, args.batchsize, repeat=False)

    updater_kw = {} if converter is None else {"converter": converter}
    updater = cmn.StandardUpdater(
        train_it, opt, loss_fn, params, comm, state=state, **updater_kw)
    trainer = cmn.Trainer(updater, (args.epoch, "epoch"), out=args.out)

    def metrics_fn(bundle, x, y):
        params, state = bundle
        if resnet:
            logits, _ = resnet_apply(cfg, params, state, x, train=False)
        else:
            logits = convnet_apply(cfg, params, x)
        return {"loss": softmax_cross_entropy(logits, y),
                "accuracy": accuracy(logits, y)}

    evaluator = cmn.create_multi_node_evaluator(
        cmn.Evaluator(
            test_it, metrics_fn, comm,
            get_params=lambda tr: (tr.updater.params, tr.updater.state)),
        comm)
    trainer.extend(evaluator, trigger=(1, "epoch"))
    log = cmn.LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    if comm.rank == 0:
        trainer.extend(cmn.PrintReport(
            ["epoch", "main/loss", "validation/loss",
             "validation/accuracy", "elapsed_time"], log_report=log))

    trainer.run()
    if comm.rank == 0 and log.log:
        last = log.log[-1]
        print(f"final validation accuracy: "
              f"{last.get('validation/accuracy', float('nan')):.4f}")
    return log


if __name__ == "__main__":
    main()
