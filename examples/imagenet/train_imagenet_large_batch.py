"""Large-batch ResNet-50 recipe — the "15-minute ImageNet" configuration
(BASELINE.md config 5; reference: Akiba, Suzuki, Fukuda,
arXiv:1711.04325, built on ChainerMN's fp16 allreduce + double-buffering
optimizer; reference code paths ``chainermn/optimizers.py``
``_DoubleBufferingOptimizer`` — unverified, mount empty, see SURVEY.md).

The recipe, TPU-native:

- **linear LR scaling**: lr = base_lr × (global_batch / 256)
  (Goyal et al.; the paper trained batch 32k at lr 12.5-equivalent);
- **gradual warmup**: LR ramps linearly from base_lr to the scaled LR
  over the first ``--warmup-epochs`` epochs, then polynomial/cosine
  decay — avoids early divergence at large batch;
- **low-precision allreduce**: ``allreduce_grad_dtype=bfloat16`` — the
  bf16 analogue of the paper's fp16 gradient exchange (cast is fused
  into the XLA collective; no CuPy packing kernels needed);
- **double buffering**: 1-step-stale averaged gradients
  (``double_buffering=True``) so the gradient collective of step *i*
  overlaps step *i+1*'s fwd/bwd — the paper's overlap trick as pure
  optax state instead of threads+streams;
- **layer-wise adaptive rates**: ``--optimizer lars`` (You et al. 2017,
  the optimizer that pushed ResNet-50 past batch 32k) or ``lamb``;
  composes inside ``create_multi_node_optimizer`` like any inner optax
  transformation;
- **fused dispatch**: ``--steps-per-execution N`` runs N steps per XLA
  call (``fuse_steps``) to amortise host dispatch latency;
- **preemption safety**: ``--resumable`` adds the checkpointer + the
  SIGTERM ``PreemptionCheckpointer`` so a reclaimed TPU slice saves at
  the signal and the restarted job resumes where it stopped.

Runnable end-to-end on the virtual CPU pod with ``--tiny --platform
cpu`` (the schedule/staleness composition is what matters; throughput
needs chips).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from train_imagenet import make_dataset  # noqa: E402  (sibling example)


def make_lr_schedule(base_lr, global_batch, warmup_epochs, total_epochs,
                     steps_per_epoch):
    """Linear-scaling + gradual-warmup + cosine-decay schedule."""
    import optax

    scaled = base_lr * global_batch / 256.0
    warmup_steps = max(int(warmup_epochs * steps_per_epoch), 1)
    decay_steps = max(
        int((total_epochs - warmup_epochs) * steps_per_epoch), 1)
    return optax.join_schedules(
        [optax.linear_schedule(base_lr, scaled, warmup_steps),
         optax.cosine_decay_schedule(scaled, decay_steps)],
        boundaries=[warmup_steps])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--communicator", default="tpu_xla")
    p.add_argument("--batchsize", type=int, default=1024,
                   help="global batch (the paper used 32k over 1024 GPUs)")
    p.add_argument("--epoch", type=int, default=4)
    p.add_argument("--base-lr", type=float, default=0.1)
    p.add_argument("--warmup-epochs", type=float, default=1.0)
    p.add_argument("--no-double-buffering", action="store_true")
    p.add_argument("--optimizer", default="sgd",
                   choices=["sgd", "lars", "lamb"],
                   help="inner optimizer; lars/lamb are the layer-wise "
                        "adaptive large-batch recipes")
    p.add_argument("--steps-per-execution", type=int, default=1,
                   help="train steps fused into one XLA dispatch")
    p.add_argument("--resumable", action="store_true",
                   help="periodic + preemption (SIGTERM) checkpoints "
                        "under --out, with automatic resume")
    p.add_argument("--grad-dtype", default="bfloat16")
    p.add_argument("--train-npz", default=None)
    p.add_argument("--platform", default=None)
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--out", default="result_large_batch")
    args = p.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import (
        ResNetConfig, accuracy, init_resnet, resnet_apply,
        softmax_cross_entropy,
    )

    comm = cmn.create_communicator(args.communicator)
    if comm.rank == 0:
        print(f"world: {comm.size} devices, {comm.inter_size} processes")

    if args.tiny:
        image, classes, n = 32, 8, 512
        batch = min(args.batchsize, 128)
        cfg = ResNetConfig(depth=50, num_classes=classes, width=8,
                           dtype="float32")
    else:
        image, classes, n = 224, 1000, 50000
        batch = args.batchsize
        cfg = ResNetConfig(depth=50, num_classes=classes)

    data = make_dataset(n, image, classes, npz=args.train_npz)
    from chainermn_tpu.datasets import SubDataset

    split = len(data) * 9 // 10
    train = cmn.scatter_dataset(
        SubDataset(data, np.arange(split)), comm, shuffle=True, seed=0)
    test = cmn.scatter_dataset(
        SubDataset(data, np.arange(split, len(data))), comm)

    # the iterator batch IS the global batch (the updater shards it over
    # the whole mesh), and the trainer's epoch unit is the ITERATOR's
    # epoch (one sweep of this process's shard) — both the LR scaling
    # and the schedule's step count must use those same definitions
    steps_per_epoch = max(len(train) // batch, 1)
    schedule = make_lr_schedule(
        args.base_lr, batch, args.warmup_epochs, args.epoch,
        steps_per_epoch)

    params, state = init_resnet(jax.random.PRNGKey(0), cfg)

    def loss_fn(params, state, x, y):
        logits, new_state = resnet_apply(
            cfg, params, state, x, train=True, axis_name=comm.axis_name)
        return softmax_cross_entropy(logits, y), new_state

    grad_dtype = jnp.dtype(args.grad_dtype) if args.grad_dtype else None
    inner = {
        # LARS defaults per You et al. / MLPerf: trust ratio over
        # weight-decayed grads, momentum 0.9
        "lars": lambda: optax.lars(
            schedule, weight_decay=1e-4, momentum=0.9),
        "lamb": lambda: optax.lamb(schedule, weight_decay=1e-4),
        "sgd": lambda: optax.sgd(schedule, momentum=0.9),
    }[args.optimizer]()
    opt = cmn.create_multi_node_optimizer(
        inner,
        comm,
        double_buffering=not args.no_double_buffering,
        allreduce_grad_dtype=grad_dtype,
    )

    train_it = cmn.SerialIterator(train, batch, shuffle=True, seed=1)
    test_it = cmn.SerialIterator(test, batch, repeat=False)

    updater = cmn.StandardUpdater(
        train_it, opt, loss_fn, params, comm, state=state,
        steps_per_execution=args.steps_per_execution)
    trainer = cmn.Trainer(updater, (args.epoch, "epoch"), out=args.out)

    if args.resumable:
        cp = cmn.extensions.create_multi_node_checkpointer(
            comm, args.out)
        resumed_at = cp.maybe_load(updater, trainer)
        if resumed_at is not None and comm.rank == 0:
            # explicit marker so resume tests can't pass vacuously
            # (a silently-inert checkpoint path would retrain from
            # scratch bit-identically on deterministic configs)
            print(f"resumed at iteration {resumed_at}")
        trainer.extend(cp, trigger=(max(steps_per_epoch, 1), "iteration"))
        trainer.extend(cmn.extensions.PreemptionCheckpointer(cp, comm))

    def metrics_fn(bundle, x, y):
        params, state = bundle
        logits, _ = resnet_apply(cfg, params, state, x, train=False)
        return {"loss": softmax_cross_entropy(logits, y),
                "accuracy": accuracy(logits, y)}

    evaluator = cmn.create_multi_node_evaluator(
        cmn.Evaluator(
            test_it, metrics_fn, comm,
            get_params=lambda tr: (tr.updater.params, tr.updater.state)),
        comm)
    trainer.extend(evaluator, trigger=(1, "epoch"))
    log = cmn.LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    if comm.rank == 0:
        trainer.extend(cmn.PrintReport(
            ["epoch", "main/loss", "validation/loss",
             "validation/accuracy", "elapsed_time"], log_report=log))

    trainer.run()
    if comm.rank == 0 and log.log:
        last = log.log[-1]
        print(f"final validation accuracy: "
              f"{last.get('validation/accuracy', float('nan')):.4f}")
    return log


if __name__ == "__main__":
    main()
