"""MNIST MLP data-parallel training — analogue of the reference's
``examples/mnist/train_mnist.py`` (mpiexec-launched DP MLP; unverified —
mount empty, see SURVEY.md).

Launch model shift: no ``mpiexec -n N`` — ONE process drives all local
devices (run under `XLA_FLAGS=--xla_force_host_platform_device_count=8
python examples/mnist/train_mnist.py --platform cpu` to simulate a pod
slice, or plainly on a TPU host).  Multi-host pods launch the same script
per host (jax.distributed).

Uses a synthetic MNIST-shaped dataset when torchvision/real data is
unavailable (zero-egress environments); pass --mnist-npz to point at a
downloaded mnist.npz.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_dataset(npz_path=None, n=4096, seed=0):
    import numpy as np

    if npz_path and os.path.exists(npz_path):
        d = np.load(npz_path)
        train = list(zip(d["x_train"].astype("float32") / 255.0,
                         d["y_train"].astype("int32")))
        test = list(zip(d["x_test"].astype("float32") / 255.0,
                        d["y_test"].astype("int32")))
        return train, test
    # synthetic, linearly-separable-ish 10-class images
    rng = np.random.RandomState(seed)
    protos = rng.randn(10, 784).astype("float32")
    xs = []
    for i in range(n):
        c = i % 10
        xs.append((protos[c] + 0.3 * rng.randn(784).astype("float32"),
                   np.int32(c)))
    return xs[: n * 9 // 10], xs[n * 9 // 10:]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--communicator", default="tpu_xla")
    p.add_argument("--batchsize", type=int, default=128)
    p.add_argument("--epoch", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--platform", default=None,
                   help="force jax platform (cpu for the virtual pod)")
    p.add_argument("--mnist-npz", default=None)
    p.add_argument("--out", default="result")
    args = p.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import jax
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import (accuracy, init_mlp, mlp_apply,
                                      softmax_cross_entropy)

    comm = cmn.create_communicator(args.communicator)
    if comm.rank == 0:
        print(f"world: {comm.size} devices, {comm.inter_size} processes")

    train, test = make_dataset(args.mnist_npz)
    train = cmn.scatter_dataset(train, comm, shuffle=True, seed=0)
    test = cmn.scatter_dataset(test, comm)

    train_it = cmn.SerialIterator(train, args.batchsize, shuffle=True, seed=1)
    test_it = cmn.SerialIterator(test, args.batchsize, repeat=False)

    params = init_mlp(jax.random.PRNGKey(0), [784, 256, 256, 10])
    opt = cmn.create_multi_node_optimizer(optax.sgd(args.lr), comm)

    def loss_fn(params, x, y):
        return softmax_cross_entropy(mlp_apply(params, x), y)

    def metrics_fn(params, x, y):
        logits = mlp_apply(params, x)
        return {"loss": softmax_cross_entropy(logits, y),
                "accuracy": accuracy(logits, y)}

    updater = cmn.StandardUpdater(train_it, opt, loss_fn, params, comm)
    trainer = cmn.Trainer(updater, (args.epoch, "epoch"), out=args.out)

    evaluator = cmn.create_multi_node_evaluator(
        cmn.Evaluator(test_it, metrics_fn, comm), comm)
    trainer.extend(evaluator, trigger=(1, "epoch"))
    log = cmn.LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    if comm.rank == 0:  # rank-0-only printing, the reference's convention
        trainer.extend(cmn.PrintReport(
            ["epoch", "main/loss", "validation/loss", "validation/accuracy",
             "elapsed_time"], log_report=log))

    trainer.run()
    if comm.rank == 0 and log.log:
        last = log.log[-1]
        print(f"final validation accuracy: "
              f"{last.get('validation/accuracy', float('nan')):.4f}")
    return log


if __name__ == "__main__":
    main()
