"""Model-parallel MNIST — analogue of the reference's model-parallel MNIST
example built on ``MultiNodeChainList`` (reference: ``examples/``; unverified
— mount empty, see SURVEY.md).

The MLP is split across TWO pipeline ranks: rank 0 owns the first half,
rank 1 the second; activations flow 0→1 by ``ppermute`` and gradients flow
back automatically (no ``pseudo_connect`` — see links/multi_node_chain_list
docstring).  Every other mesh device is a data-parallel replica: the mesh
is ``(pipe=2, data=world/2)``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from train_mnist import make_dataset  # noqa: E402  (same dataset)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batchsize", type=int, default=128)
    p.add_argument("--epoch", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu as cmn
    from chainermn_tpu.links import MultiNodeChainList
    from chainermn_tpu.models import (
        accuracy, init_mlp, mlp_apply, softmax_cross_entropy,
    )
    from chainermn_tpu.parallel import MeshConfig

    n_dev = len(jax.devices())
    if n_dev < 2:
        print(f"needs >=2 devices for pipe=2, have {n_dev} — exiting")
        return None
    mc = MeshConfig(pipe=2, data=n_dev // 2)
    print(f"mesh: {mc}")

    # two-stage MLP over the pipe axis (the MultiNodeChainList graph)
    mn = MultiNodeChainList(axis_name="pipe")
    mn.add_link(
        lambda k: init_mlp(k, [784, 256, 256]),
        mlp_apply, owner=0, rank_out=1, name="lower_half")
    mn.add_link(
        lambda k: init_mlp(k, [256, 10]),
        mlp_apply, owner=1, rank_in=0, name="upper_half")
    params = mn.init(jax.random.PRNGKey(0))

    train, test = make_dataset()
    xs = np.stack([x for x, _ in train])
    ys = np.stack([y for _, y in train])
    xt = np.stack([x for x, _ in test])
    yt = np.stack([y for _, y in test])

    opt = optax.sgd(args.lr)
    opt_state = opt.init(params)

    def sharded_step(params, x, y):
        def loss_of(ps):
            logits = mn.apply(ps, x)
            # batch is data-sharded → pmean over data; pipe-replicated loss
            return jax.lax.pmean(
                softmax_cross_entropy(logits, y), "data")

        loss, grads = jax.value_and_grad(loss_of)(params)
        grads = mn.reduce_grads(grads)   # keep replicas consistent
        return loss, grads

    grad_fn = jax.shard_map(
        sharded_step, mesh=mc.mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()))

    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = grad_fn(params, x, y)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def eval_logits(params, x):
        return jax.shard_map(
            lambda ps, xx: mn.apply(ps, xx),
            mesh=mc.mesh, in_specs=(P(), P("data")), out_specs=P("data"),
        )(params, x)

    dp = mc.axis_size("data")
    bs = max(args.batchsize // dp, 1) * dp   # divisible by the data axis
    n_eval = len(xt) // dp * dp
    n_batches = len(xs) // bs
    for epoch in range(args.epoch):
        perm = np.random.RandomState(epoch).permutation(len(xs))
        total = 0.0
        for i in range(n_batches):
            idx = perm[i * bs:(i + 1) * bs]
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(xs[idx]),
                jnp.asarray(ys[idx]))
            total += float(loss)
        logits = eval_logits(params, jnp.asarray(xt[:n_eval]))
        acc = float(accuracy(logits, jnp.asarray(yt[:n_eval])))
        print(f"epoch={epoch + 1}  main/loss={total / n_batches:.4f}  "
              f"validation/accuracy={acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
