"""Flagship transformer LM training — every parallel axis from one CLI.

The reference had no transformer (it predates them); this example is
the integration showcase its `examples/` directory played for the DP
era: one script that composes DP × TP × PP × SP × EP on a single
`MeshConfig`, with the trainer/checkpoint stack around it.

Synthetic data with learnable structure (an affine next-token rule
plus noise) so the loss measurably falls within a smoke run — the same
role the reference's synthetic/MNIST data played.

Examples (virtual 8-device pod — export the fake-device flag first):

    export JAX_PLATFORMS=cpu
    export XLA_FLAGS=--xla_force_host_platform_device_count=8

    # DP only
    python train_lm.py --platform cpu --mesh data=8 --steps 30
    # 2-way tensor x 2-way sequence (ring attention) x 2-way data
    python train_lm.py --platform cpu --mesh data=2,model=2,seq=2 \
        --attention ring --steps 30
    # 2-stage 1F1B pipeline x 4-way data, GQA + RoPE
    python train_lm.py --platform cpu --mesh pipe=2,data=4 \
        --schedule 1f1b --n-kv-heads 2 --pos-embedding rope --steps 30
    # Switch-MoE over a 2-way expert axis
    python train_lm.py --platform cpu --mesh data=4,expert=2 --moe
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def parse_mesh(spec: str):
    axes = {}
    for part in filter(None, spec.split(",")):
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    return axes


def check_text_args(path, vocab, seq, tokenized=False):
    """Fail fast on --text-file misconfiguration: called right after
    argument parsing, BEFORE the mesh/params/compile work, so a typo'd
    path or too-small vocab costs seconds, not a full model setup."""
    if vocab < 256 and not tokenized:
        raise SystemExit(
            f"--text-file is byte-level: --vocab {vocab} must be >= 256"
            " (or pass --tokenizer-vocab for a subword vocabulary)")
    if not os.path.exists(path):
        raise SystemExit(f"--text-file {path}: no such file")
    if os.path.getsize(path) < seq + 1:
        raise SystemExit(
            f"{path}: {os.path.getsize(path)} bytes < seq+1 = {seq + 1}")


def _text_windows(data, batch, seq, steps, seed):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        starts = rng.randint(0, data.size - seq, batch)
        x = np.stack([data[s:s + seq + 1] for s in starts]).astype(
            np.int32)
        yield x[:, :-1], x[:, 1:]


def load_text(path, vocab, seq):
    """Byte corpus split 90/10 into train/held-out ranges (held-out =
    the file's TAIL, never sampled by training, so the reported
    perplexity is honest).  A tail too small for one window folds into
    training and disables eval."""
    check_text_args(path, vocab, seq)
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    cut = int(0.9 * data.size)
    # either side too small for one window => no split, no eval
    if cut < seq + 1 or data.size - cut < seq + 1:
        return data, None
    return data[:cut], data[cut:]


# byte-level real-data contract: bytes ARE the tokens (ids 0-255, so
# --vocab must be >= 256; spare ids go unused); each batch row is a
# random contiguous (seq+1)-byte window over the TRAIN split.  The
# reference's examples consumed real files the same minimal way (no
# tokenizer dependency).  The corpus is read ONCE (load_text) and the
# train/held-out arrays passed around — re-reading between training
# and eval could silently split different file contents.


def load_text_tokenized(path, tok_vocab, seq, ckpt_dir):
    """--tokenizer-vocab path: split the RAW BYTES 90/10 first (the
    held-out text is the same regardless of tokenizer choices), train
    a byte-level BPE on the train split only (training it on held-out
    bytes would leak tail statistics into the vocabulary), then encode
    both sides.  Merges persist as ``bpe.json`` beside the checkpoint;
    a resume loads them instead of retraining — token ids must mean
    the same thing across runs or the resumed model is garbage."""
    from chainermn_tpu.datasets import BPETokenizer, train_bpe

    check_text_args(path, 256, seq, tokenized=True)
    with open(path, "rb") as f:
        raw = f.read()
    cut = int(0.9 * len(raw))
    bpe_path = os.path.join(ckpt_dir, "bpe.json") if ckpt_dir else None
    if bpe_path and os.path.exists(bpe_path):
        tok = BPETokenizer.load(bpe_path)
        if tok.vocab_size > tok_vocab:
            raise SystemExit(
                f"{bpe_path} holds {tok.vocab_size} ids > "
                f"--tokenizer-vocab {tok_vocab}: stale tokenizer from "
                "an earlier run — delete the file or match the flag")
        print(f"loaded tokenizer {bpe_path} ({tok.vocab_size} ids; "
              "delete the file to retrain)")
    else:
        t0 = time.perf_counter()
        tok = train_bpe(raw[:cut], tok_vocab)
        print(f"trained BPE: {tok.vocab_size} ids "
              f"({time.perf_counter() - t0:.1f}s)")
        if bpe_path:
            os.makedirs(ckpt_dir, exist_ok=True)
            tok.save(bpe_path)
            print(f"saved {bpe_path}")
    train = np.asarray(tok.encode(raw[:cut]), np.int32)
    held = np.asarray(tok.encode(raw[cut:]), np.int32)
    if train.size < seq + 1:
        raise SystemExit(
            f"{path}: {train.size} train tokens < seq+1 = {seq + 1}")
    if held.size < seq + 1:
        held = None
    return train, held, tok


def make_batches(vocab, batch, seq, steps, seed=0):
    """Sequences following tok[t+1] = (a*tok[t] + b) % vocab with 10%
    noise — enough structure that a few dozen steps visibly cut loss."""
    rng = np.random.RandomState(seed)
    a, b = 7, 3
    for _ in range(steps):
        x = np.empty((batch, seq + 1), np.int32)
        x[:, 0] = rng.randint(0, vocab, batch)
        for t in range(seq):
            nxt = (a * x[:, t] + b) % vocab
            noise = rng.randint(0, vocab, batch)
            take = rng.rand(batch) < 0.1
            x[:, t + 1] = np.where(take, noise, nxt)
        yield x[:, :-1], x[:, 1:]


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mesh", default="data=8",
                   help="comma list, e.g. data=2,model=2,seq=2")
    p.add_argument("--attention", default="local",
                   choices=["local", "flash", "ring", "ulysses"])
    p.add_argument("--schedule", default="gpipe",
                   choices=["gpipe", "1f1b", "interleaved"])
    p.add_argument("--pos-embedding", default="learned",
                   choices=["learned", "rope"])
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--window", type=int, default=0)
    p.add_argument("--text-file", default=None,
                   help="train on a REAL text file, byte-level tokens "
                        "(needs --vocab >= 256); default is synthetic "
                        "data")
    p.add_argument("--tokenizer-vocab", type=int, default=0,
                   help="with --text-file: train/load a byte-level BPE "
                        "subword vocabulary of up to this many ids "
                        "(0 = raw bytes).  Merges persist as bpe.json "
                        "beside --checkpoint and round-trip through "
                        "generate.py --tokenizer; held-out perplexity "
                        "is then reported per token AND per byte")
    p.add_argument("--loss-chunk", type=int, default=0,
                   help="chunked-vocab cross-entropy chunk size "
                        "(0 = whole-shard logits)")
    p.add_argument("--vocab-parallel", action="store_true",
                   help="shard the tied embedding's vocab dim over the "
                        "model axis (Megatron vocab TP)")
    p.add_argument("--moe", action="store_true")
    p.add_argument("--router-top-k", type=int, default=1,
                   help="experts per token (1=Switch, 2=GShard top-2)")
    p.add_argument("--seq-layout", default="contiguous",
                   choices=["contiguous", "zigzag"])
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO-3/FSDP: shard params+grads+optimiser "
                        "state over the data axis (d_model must divide "
                        "by it); weights all-gather per layer")
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--batchsize", type=int, default=32)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--checkpoint", default=None,
                   help="directory for a final-state snapshot (resumes "
                        "from it if one exists; for in-run periodic + "
                        "preemption checkpoints see "
                        "extensions.MultiNodeCheckpointer)")
    p.add_argument("--platform", default=None)
    args = p.parse_args()
    if args.tokenizer_vocab and not args.text_file:
        raise SystemExit("--tokenizer-vocab needs --text-file")
    if args.tokenizer_vocab and args.tokenizer_vocab <= 256:
        raise SystemExit(
            f"--tokenizer-vocab {args.tokenizer_vocab} must exceed 256 "
            "(ids 0-255 are the raw bytes; merges come on top)")
    if args.text_file:
        # fail fast, before the mesh/compile work
        check_text_args(args.text_file, args.vocab, args.seq,
                        tokenized=bool(args.tokenizer_vocab))

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp
    import optax

    from chainermn_tpu.models import (
        TransformerConfig, init_transformer, make_train_step,
        shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig
    from chainermn_tpu.training import shard_opt_state
    from chainermn_tpu.utils.serialization import load_state, save_state

    tok = tok_train = tok_held = None
    if args.text_file and args.tokenizer_vocab:
        # before cfg: the learned vocabulary decides the model's vocab
        tok_train, tok_held, tok = load_text_tokenized(
            args.text_file, args.tokenizer_vocab, args.seq,
            args.checkpoint)
        vocab = max(args.vocab, -(-tok.vocab_size // 128) * 128)
        if vocab != args.vocab:
            print(f"model vocab {vocab} (tokenizer {tok.vocab_size} "
                  "ids, padded up to a 128-multiple for clean "
                  "sharding and MXU tiling)")
            args.vocab = vocab

    axes = parse_mesh(args.mesh)
    mc = MeshConfig(**axes)
    pipe = axes.get("pipe", 1)
    V = 2 if args.schedule == "interleaved" else 1
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, d_head=args.d_model // args.n_heads,
        n_kv_heads=args.n_kv_heads, d_ff=4 * args.d_model,
        n_layers=args.n_layers, max_seq=args.seq,
        attention=args.attention,
        attention_window=args.window,
        pos_embedding=args.pos_embedding,
        seq_layout=args.seq_layout,
        moe=args.moe, n_experts=max(2 * axes.get("expert", 1), 2),
        router_top_k=args.router_top_k if args.moe else 1,
        loss_chunk=args.loss_chunk,
        vocab_parallel=args.vocab_parallel,
        num_microbatches=2 if pipe > 1 else 1,
        pipeline_schedule=args.schedule, virtual_pipe=V,
        fsdp=args.fsdp,
        dtype="float32", remat=False,
    )
    opt = optax.adamw(args.lr)
    start = 0
    ckpt_file = (os.path.join(args.checkpoint, "lm_state.npz")
                 if args.checkpoint else None)
    saved = (load_state(ckpt_file)
             if ckpt_file and os.path.exists(ckpt_file) else None)
    saved_pipe = int(saved.get("pipe", pipe)) if saved else pipe
    saved_v = int(saved.get("virtual_pipe", V)) if saved else V
    if saved is not None and (saved_pipe, saved_v) != (pipe, V):
        # elastic resume: the checkpoint was grouped for a different
        # pipe mesh — regroup the block stack and re-lay params + Adam
        # state onto THIS mesh (reference parity was identical world
        # size only; see models.reshard_train_state).  No fresh init on
        # this path: a second full state resident next to the resharded
        # one would double peak memory exactly where large models hurt.
        from chainermn_tpu.models import reshard_train_state

        params, opt_state = reshard_train_state(
            mc, cfg, opt, saved["params"], saved["opt"],
            from_pipe=saved_pipe, from_virtual=saved_v)
        print(f"regrouped checkpoint pipe={saved_pipe}/V={saved_v} "
              f"-> pipe={pipe}/V={V}")
    else:
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, pipe))
        # pins the state's shardings to the params' (with --fsdp the
        # Adam moments land shard-width; plain jit(init) would
        # replicate them)
        opt_state = shard_opt_state(opt, params)
        if saved is not None:
            # same grouping: re-place on the mesh via device_put against
            # the freshly built (correctly sharded) state, NOT bare
            # jnp.asarray — with --fsdp that would re-materialise params
            # AND both Adam moments replicated, forfeiting exactly the
            # residency the flag buys
            def replace_like(saved_tree, like_tree):
                return jax.tree.map(
                    lambda saved_leaf, like: jax.device_put(
                        jnp.asarray(saved_leaf), like.sharding),
                    saved_tree, like_tree)

            params = replace_like(saved["params"], params)
            opt_state = replace_like(saved["opt"], opt_state)
    if saved is not None:
        start = int(saved["step"])
        print(f"resumed at step {start}")
    step = make_train_step(mc, cfg, opt)
    if start >= args.steps:
        print(f"nothing to do: resumed step {start} >= --steps "
              f"{args.steps}")
        return None

    # zigzag layout contract: the model expects tokens permuted by
    # zigzag_indices (device r holds chunks r and 2S-1-r, balancing the
    # causal ring); inputs AND targets permute identically, so the
    # next-token alignment is preserved
    perm = None
    if args.seq_layout == "zigzag":
        from chainermn_tpu.parallel import zigzag_indices

        perm = zigzag_indices(axes.get("seq", 1), args.seq).reshape(-1)

    heldout = None
    if args.text_file:
        if tok is not None:
            train_data, heldout = tok_train, tok_held
        else:
            train_data, heldout = load_text(
                args.text_file, args.vocab, args.seq)
        batches = _text_windows(
            train_data, args.batchsize, args.seq,
            args.steps - start, seed=start)
    else:
        batches = make_batches(args.vocab, args.batchsize, args.seq,
                               args.steps - start, seed=start)
    first = last = None
    t0 = time.perf_counter()
    for i, (x, y) in enumerate(batches):
        if perm is not None:
            x, y = x[:, perm], y[:, perm]
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(x), jnp.asarray(y))
        loss = float(loss)
        if first is None:
            first = loss
        last = loss
        if (start + i) % 10 == 0:
            print(f"step {start + i:4d}  loss {loss:.4f}")
    print(f"loss {first:.4f} -> {last:.4f} over {args.steps - start} "
          f"steps ({time.perf_counter() - t0:.1f}s) on mesh {mc}")

    if not np.isfinite(last):
        # never persist a diverged state — a resume would train from it
        # (and a held-out eval of diverged params would just print nan)
        raise SystemExit("non-finite loss")

    if args.text_file:
        # held-out perplexity on the file's tail (never sampled by
        # training) — the honest generalisation number for the run.
        # With a tokenizer, report per-token AND per-byte: per-byte
        # (exp of total nll over decoded byte count) is the number
        # comparable across vocabularies, byte-level runs included.
        if heldout is None:
            print("held-out eval skipped: file too small for a 90/10 "
                  "split at this --seq")
        else:
            from chainermn_tpu.models import make_forward_fn

            fwd = make_forward_fn(mc, cfg)
            total_nll = total_tokens = total_bytes = 0.0
            for x, y in _text_windows(
                    heldout, args.batchsize, args.seq, 4, seed=99):
                if perm is not None:
                    x, y = x[:, perm], y[:, perm]
                logp = np.asarray(jax.nn.log_softmax(
                    fwd(params, jnp.asarray(x)), axis=-1))
                total_nll += float(-np.take_along_axis(
                    logp, np.asarray(y)[..., None], axis=-1).sum())
                total_tokens += y.size
                total_bytes += (tok.n_bytes(y.reshape(-1))
                                if tok is not None else y.size)
            tok_ppl = float(np.exp(total_nll / total_tokens))
            byte_ppl = float(np.exp(total_nll / total_bytes))
            if tok is not None:
                print(f"held-out token perplexity {tok_ppl:.2f} "
                      f"(uniform over the {tok.vocab_size} tokenizer "
                      f"ids would be {tok.vocab_size}); "
                      f"byte perplexity {byte_ppl:.2f} at "
                      f"{total_bytes / total_tokens:.2f} bytes/token")
            else:
                print(f"held-out byte perplexity {byte_ppl:.2f} "
                      f"(uniform would be {args.vocab})")
    if ckpt_file:
        save_state(ckpt_file, {
            "params": jax.tree.map(np.asarray, params),
            "opt": jax.tree.map(np.asarray, opt_state),
            "step": args.steps,
            # the pipe grouping this state was SAVED with, so a resume
            # on a different mesh knows how to regroup (elastic resume)
            "pipe": pipe,
            "virtual_pipe": V,
        })
        print(f"saved {ckpt_file}")
    return last


if __name__ == "__main__":
    main()
