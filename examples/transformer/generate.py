"""Text generation with the flagship transformer — KV cache, beam
search, and weight-only int8 from one CLI.

The reference's only generation path was the seq2seq example's greedy
LSTM loop; this is its transformer-era counterpart.  Runs from a
checkpoint written by ``train_lm.py`` (so `train → generate` is a
complete loop) or from random init for a smoke run.

Examples (virtual pod or real chip):

    # greedy, from a train_lm.py checkpoint
    python generate.py --checkpoint ck --prompt 5,11,2 --max-len 32
    # temperature sampling, 2-way tensor-parallel mesh
    python generate.py --mesh data=4,model=2 --temperature 0.8
    # beam search over int8-quantized weights
    python generate.py --beam 4 --int8
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from train_lm import parse_mesh  # noqa: E402  (sibling example)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mesh", default="data=-1",
                   help="decode meshes shard batch (data/expert), "
                        "heads (model), and layers + KV cache (pipe — "
                        "S-phase hand-off, S-fold model capacity); "
                        "seq must be 1")
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--pos-embedding", default="learned",
                   choices=["learned", "rope"])
    p.add_argument("--max-len", type=int, default=32)
    p.add_argument("--prompt", default="1,2,3",
                   help="comma-separated token ids (one sequence, "
                        "repeated across the batch)")
    p.add_argument("--tokenizer", default=None,
                   help="bpe.json written by train_lm.py "
                        "--tokenizer-vocab: enables --prompt-text and "
                        "decodes generated ids back to text (pass the "
                        "same --vocab the training run printed)")
    p.add_argument("--prompt-text", default=None,
                   help="text prompt, encoded with --tokenizer "
                        "(overrides --prompt)")
    p.add_argument("--prompt-file", default=None,
                   help="file with ONE prompt per line — text (with "
                        "--tokenizer) or comma-separated ids; rows may "
                        "have different lengths (right-aligned with "
                        "padding, decoded via prompt_lens); the batch "
                        "is the line count")
    p.add_argument("--batchsize", type=int, default=8)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0,
                   help="sample from the k best tokens only (0 = off)")
    p.add_argument("--top-p", type=float, default=1.0,
                   help="nucleus sampling mass cutoff (1.0 = off)")
    p.add_argument("--eos-id", type=int, default=-1,
                   help="early stopping: rows that emit this token "
                        "freeze (later positions = --pad-id) and "
                        "generation exits when every row is done")
    p.add_argument("--pad-id", type=int, default=0)
    p.add_argument("--beam", type=int, default=0,
                   help="beam size; 0 = greedy/sampling")
    p.add_argument("--speculative-k", type=int, default=0,
                   help="speculative decoding: draft proposes k tokens "
                        "per round (0 = off); output is token-identical "
                        "to plain greedy")
    p.add_argument("--draft-layers", type=int, default=0,
                   help="draft model depth (default n_layers/2)")
    p.add_argument("--lookup-k", type=int, default=0,
                   help="prompt-lookup decoding: propose k tokens from "
                        "the last n-gram's most recent earlier "
                        "occurrence in the context — speculative "
                        "decoding with NO draft model; output is "
                        "token-identical to plain greedy")
    p.add_argument("--lookup-ngram", type=int, default=2)
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 decode")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache with per-(token, head) scales: "
                        "half the cache HBM (the long-context decode "
                        "bound); composes with --int8 weights")
    p.add_argument("--vocab-parallel", action="store_true",
                   help="shard the tied embedding over the model axis "
                        "(serving-side Megatron vocab TP: V/M embed "
                        "rows resident per device)")
    p.add_argument("--checkpoint", default=None,
                   help="train_lm.py checkpoint dir to load params from")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models import (
        TransformerConfig, init_transformer, make_beam_search_fn,
        make_generate_fn, quantize_params_int8, shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig
    from chainermn_tpu.utils.serialization import load_state

    mc = MeshConfig(**parse_mesh(args.mesh))
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, d_head=args.d_model // args.n_heads,
        n_kv_heads=args.n_kv_heads, d_ff=4 * args.d_model,
        n_layers=args.n_layers, max_seq=args.max_len,
        attention="local", pos_embedding=args.pos_embedding,
        vocab_parallel=args.vocab_parallel,
        kv_cache_dtype="int8" if args.kv_int8 else "",
        dtype="float32", remat=False,
    )

    ckpt_file = (os.path.join(args.checkpoint, "lm_state.npz")
                 if args.checkpoint else None)
    pipe = mc.mesh.shape.get("pipe", 1)
    if ckpt_file and os.path.exists(ckpt_file):
        from chainermn_tpu.models import regroup_blocks

        saved = load_state(ckpt_file)
        params = jax.tree.map(jnp.asarray, saved["params"])
        # checkpoints store blocks grouped for whatever pipe mesh
        # TRAINED them ((P0, L/P0, ...), or (P0, V0, lpc, ...) from an
        # interleaved run — the snapshot records its grouping): regroup
        # to this decode mesh's pipe size (a pipe-trained checkpoint
        # must decode on a pipe=1 mesh too, and vice versa).  Legacy
        # snapshots without the metadata are plain-grouped: P0 is the
        # blocks' leading dim.
        first = jax.tree.leaves(params["blocks"])[0]
        saved_pipe = int(saved.get("pipe", first.shape[0]))
        saved_v = int(saved.get("virtual_pipe", 1))
        params = dict(params, blocks=regroup_blocks(
            params["blocks"], saved_pipe, pipe, saved_v, 1))
        print(f"loaded {ckpt_file}")
        ckpt_loaded = True
    else:
        params = init_transformer(
            jax.random.PRNGKey(args.seed), cfg, pipe)
        ckpt_loaded = False
    if args.int8:
        params = quantize_params_int8(cfg, params)
    # keep the pre-shard host tree ONLY when the speculative draft will
    # slice layers from it (that must happen BEFORE sharding — on a
    # multi-process mesh the sharded leaves are not fully addressable
    # from any single host); otherwise let it free after placement
    host_params = params if args.speculative_k > 0 else None
    params = shard_params(mc, cfg, params)

    tok = None
    if args.tokenizer:
        from chainermn_tpu.datasets import BPETokenizer

        tok = BPETokenizer.load(args.tokenizer)

    def check_ids(ids, what):
        if not ids or any(not 0 <= t < args.vocab for t in ids):
            raise SystemExit(
                f"{what}: prompt ids must be in [0, {args.vocab}) "
                f"and non-empty")
        return ids

    def parse_int_ids(text, what):
        try:
            return [int(t) for t in text.split(",") if t.strip()]
        except ValueError:
            raise SystemExit(
                f"{what}: expected comma-separated token ids (got "
                f"{text[:40]!r}) — for text prompts pass --tokenizer")

    prompt_lens = None
    if args.prompt_file is not None:
        rows = []
        with open(args.prompt_file) as f:
            for i, ln in enumerate(f):
                if not ln.strip():
                    continue          # blank lines skipped, numbering
                ln = ln.rstrip("\r\n")  # CRLF-safe; numbering physical
                rows.append(check_ids(
                    tok.encode(ln) if tok is not None else
                    parse_int_ids(ln, f"line {i + 1}"),
                    f"line {i + 1}"))
        if not rows:
            raise SystemExit(f"{args.prompt_file}: no prompts in file")
        dshard = mc.mesh.shape.get("data", 1) \
            * mc.mesh.shape.get("expert", 1)
        if len(rows) % dshard:
            raise SystemExit(
                f"{args.prompt_file}: {len(rows)} prompts do not "
                f"divide over the mesh's data×expert axes ({dshard}) "
                "— pad the file or pick a smaller --mesh")
        P_len = max(len(r) for r in rows)
        prompt_lens = np.asarray([len(r) for r in rows])
        prompt = np.zeros((len(rows), P_len), np.int32)
        for b, r in enumerate(rows):      # right-aligned
            prompt[b, P_len - len(r):] = r
        prompt = jnp.asarray(prompt)
    else:
        if args.prompt_text is not None:
            if tok is None:
                raise SystemExit("--prompt-text needs --tokenizer")
            toks = tok.encode(args.prompt_text)
        else:
            toks = parse_int_ids(args.prompt, "--prompt")
        check_ids(toks, "--prompt")
        prompt = jnp.asarray(
            np.tile(np.asarray(toks, np.int32), (args.batchsize, 1)))

    def show(ids, label="generated"):
        print(f"{label}:", list(map(int, ids)))
        if tok is not None:
            print(f"{label} text:", repr(tok.decode_text(ids)))

    if args.lookup_k > 0 and (args.speculative_k > 0 or args.beam > 0):
        raise SystemExit(
            "--lookup-k is its own decode mode; drop --speculative-k/"
            "--beam")
    if args.lookup_k > 0 and (args.temperature > 0 or args.top_k > 0
                              or args.top_p < 1.0):
        raise SystemExit(
            "--lookup-k is exact-GREEDY decoding; --temperature/"
            "--top-k/--top-p have no effect there — drop them (for "
            "sampled speculation use --speculative-k)")

    def show_batch(out_np):
        """Per-row display for ragged batches, first row otherwise."""
        if prompt_lens is not None:
            for b in range(out_np.shape[0]):
                start = prompt.shape[1] - int(prompt_lens[b])
                show(out_np[b, start:].tolist(), label=f"row {b}")
        else:
            show(out_np[0].tolist())

    if args.lookup_k > 0:
        from chainermn_tpu.models import make_lookup_generate_fn

        lk = make_lookup_generate_fn(
            mc, cfg, k=args.lookup_k, ngram=args.lookup_ngram,
            max_len=args.max_len, eos_id=args.eos_id,
            pad_id=args.pad_id, quantized=args.int8, with_stats=True)
        out, mean_acc = lk(params, prompt, prompt_lens=prompt_lens)
        print(f"prompt-lookup k={args.lookup_k} "
              f"ngram={args.lookup_ngram}: mean accepted "
              f"proposals/round {float(mean_acc):.2f} "
              f"(~{float(mean_acc) + 1:.2f} tokens per target read)")
        show_batch(np.asarray(out))
    elif args.speculative_k > 0:
        import dataclasses

        from chainermn_tpu.models import make_speculative_generate_fn

        d_layers = args.draft_layers or max(1, args.n_layers // 2)
        d_cfg = dataclasses.replace(cfg, n_layers=d_layers)
        if ckpt_loaded and pipe == 1:
            # truncated draft: the checkpoint's FIRST d_layers blocks
            # with the shared embed/norms — a real (if crude) draft
            # whose acceptance reflects the trained model, unlike a
            # random init that can only demonstrate the mechanics
            d_tree = dict(host_params, blocks=jax.tree.map(
                lambda a: np.asarray(a)[:, :d_layers],
                host_params["blocks"]))
            d_params = shard_params(mc, d_cfg, d_tree)
            host_params = d_tree = None    # release the host copies
            d_quant = args.int8
            note = "draft = target's first layers"
        else:
            d_params = shard_params(mc, d_cfg, init_transformer(
                jax.random.PRNGKey(args.seed + 1), d_cfg, pipe))
            host_params = None          # unused on this branch: free it
            d_quant = False
            note = "random draft (mechanics demo — expect ~1 tok/round)"
        print(f"speculative k={args.speculative_k}, {d_layers}-layer "
              f"draft: {note}")
        spec = make_speculative_generate_fn(
            mc, cfg, d_cfg, k=args.speculative_k, max_len=args.max_len,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, eos_id=args.eos_id, pad_id=args.pad_id,
            quantized=args.int8, draft_quantized=d_quant,
            with_stats=True)
        out, mean_acc = spec(params, d_params, prompt,
                             key=jax.random.PRNGKey(args.seed),
                             prompt_lens=prompt_lens)
        print(f"mean accepted proposals/round: {float(mean_acc):.2f} "
              f"of k={args.speculative_k} "
              f"(~{float(mean_acc) + 1:.2f} tokens per target read)")
        show_batch(np.asarray(out))
    elif args.beam > 0:
        bs = make_beam_search_fn(
            mc, cfg, beam_size=args.beam, max_len=args.max_len,
            eos_id=args.eos_id, length_penalty=0.6,
            quantized=args.int8)
        out, scores = bs(params, prompt, prompt_lens=prompt_lens)
        out_np, sc = np.asarray(out), np.asarray(scores)
        if prompt_lens is not None:
            for b in range(out_np.shape[0]):    # best beam per row
                start = prompt.shape[1] - int(prompt_lens[b])
                show(out_np[b, 0, start:].tolist(),
                     label=f"row {b} best (score {sc[b, 0]:+.3f})")
        else:
            for k in range(args.beam):
                show(out_np[0, k].tolist(),
                     label=f"beam {k} (score {sc[0, k]:+.3f})")
    else:
        gen = make_generate_fn(
            mc, cfg, max_len=args.max_len,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, eos_id=args.eos_id, pad_id=args.pad_id,
            quantized=args.int8)
        out = gen(params, prompt, key=jax.random.PRNGKey(args.seed),
                  prompt_lens=prompt_lens)
        show_batch(np.asarray(out))
    return out


if __name__ == "__main__":
    main()
