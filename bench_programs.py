"""Compile-and-memory plane overhead benchmark: program ledger +
memory accountant ON vs OFF.

The ledger (``utils/programs.py``) only earns riding EVERY jit call
site in the stack — the updater step, all nine serving programs, the
autotune probes — if the steady-state hit path (signature hash + one
set lookup per call) is effectively free.  Both arms run the SAME
StandardUpdater training loop on the 8-device mesh through the
ledger-instrumented step program; the ON arm enables the
ProgramLedger AND the metrics registry (so the ``compile/calls``
counter bump per call is on the measured line), marks the loop
steady after warmup, and samples a MemoryAccountant holding the
params + optimizer-state roots every ``--sample-every`` steps (the
statusz-scrape cadence, amortized the way production amortizes it);
the OFF arm is the production default — disabled ledger (one
attribute read, straight dispatch) and disabled registry.

The ON arm also asserts the plane's own invariants every run: the
warmup compiles are all attributed (ledger label stats carry
``train/step``), and the steady timed loop records ZERO
steady-retraces — the zero-steady-state-recompile invariant this PR
pins, measured here on every bench run, not just in the test suite.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}:
value = plane-off steps/sec ÷ plane-on steps/sec ("x"; 1.0 = free).
``overhead_pct`` = (value − 1) × 100, ``within_bar`` reports the <1%
bar (docs/OBSERVABILITY.md "Compile & memory").  Arms are interleaved
timed back-to-back per round (order-alternating) and the value is
the MEDIAN of per-round off/on ratios — this box's load comes in
multi-second bursts, and a burst taxes both members of a pair while
the median discards the pairs one straddled (the bench_obs_plane
measurement shape); same hermetic child-process pattern as
bench_metrics_registry.py.  ``--check`` runs the perf regression
sentinel on the fresh record (``utils/regression.py``).
"""

import argparse
import json
import os
import sys
import time

from _bench_common import pin_platform, run_child_with_retries

METRIC = "program_ledger_overhead"
UNIT = "x"
BAR_PCT = 1.0


def run(batch=8, dim=512, hidden=2048, classes=10, n_examples=4096,
        warmup=3, iters=60, rounds=6, sample_every=16):
    import jax
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import (init_mlp, mlp_apply,
                                      softmax_cross_entropy)
    from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry
    from chainermn_tpu.utils.programs import (
        MemoryAccountant,
        ProgramLedger,
        get_ledger,
        set_ledger,
    )

    comm = cmn.create_communicator("tpu_xla")
    rng = np.random.RandomState(0)
    X = rng.randn(n_examples, dim).astype(np.float32)
    Y = (rng.rand(n_examples) * classes).astype(np.int32)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    params0 = init_mlp(jax.random.PRNGKey(0), [dim, hidden, classes])

    def make(seed=11):
        it = cmn.SerialIterator((X, Y), batch, shuffle=True, seed=seed)
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)
        return cmn.StandardUpdater(it, opt, loss_fn, params0, comm)

    def timed_arm(enabled):
        prev_reg = set_registry(MetricsRegistry(enabled=enabled))
        prev_led = set_ledger(ProgramLedger(enabled=enabled))
        acc = MemoryAccountant()
        try:
            upd = make()
            if enabled:
                upd.register_memory(accountant=acc)
            for _ in range(warmup):
                upd.update()
            jax.block_until_ready(upd.params)
            led = get_ledger()
            if enabled:
                # warmup compiled the steady program; from here on any
                # train/ compile is a retrace-storm bug
                upd.mark_steady()
            start_iter = upd.iteration
            t0 = time.perf_counter()
            for i in range(iters):
                upd.update()
                if enabled and i % sample_every == 0:
                    acc.sample()
            jax.block_until_ready(upd.params)
            dt = time.perf_counter() - t0
            stats = led.label_stats()
            return {
                "steps_per_s": (upd.iteration - start_iter) / dt,
                "compiles": led.compiles(),
                "steady_retraces": led.steady_retraces(),
                "labels": sorted(stats),
                "memory_bytes": acc.table()[-1]["high_watermark"],
            }
        finally:
            set_registry(prev_reg)
            set_ledger(prev_led)

    import statistics

    # this box's load comes in multi-second bursts that swamp any
    # single ~1s timed block, so best-of-rounds does not converge
    # here (the bench_obs_plane lesson): each round times the two
    # arms BACK-TO-BACK (order-alternating) and the reported value is
    # the MEDIAN of the per-round off/on ratios — a burst taxes both
    # members of a pair, and the median discards the pairs one
    # straddled
    best = {"on": 0.0, "off": 0.0}
    ratios = []
    on_info = None
    for r in range(rounds):
        order = (False, True) if r % 2 == 0 else (True, False)
        rates = {}
        for enabled in order:
            res = timed_arm(enabled)
            key = "on" if enabled else "off"
            rates[key] = res["steps_per_s"]
            best[key] = max(best[key], res["steps_per_s"])
            if enabled:
                on_info = res
                # the plane's own invariants, asserted per run
                assert "train/step" in res["labels"], res["labels"]
                assert res["steady_retraces"] == 0, res
                assert res["memory_bytes"] and res["memory_bytes"] > 0
            else:
                assert res["compiles"] == 0, \
                    "disabled ledger recorded compiles"
        ratios.append(rates["off"] / rates["on"])

    ratio = statistics.median(ratios)
    overhead_pct = (ratio - 1.0) * 100.0
    return {
        "metric": METRIC,
        "value": round(ratio, 4),
        "unit": UNIT,
        "vs_baseline": round(ratio, 4),
        "overhead_pct": round(overhead_pct, 3),
        "bar_pct": BAR_PCT,
        "within_bar": bool(overhead_pct < BAR_PCT),
        "off_steps_per_s": round(best["off"], 2),
        "on_steps_per_s": round(best["on"], 2),
        "round_ratios": [round(x, 4) for x in ratios],
        "compiles_on_arm": on_info["compiles"],
        "steady_retraces_on_arm": on_info["steady_retraces"],
        "ledger_labels": on_info["labels"],
        "memory_high_watermark_bytes": on_info["memory_bytes"],
        "batch": batch,
        "dim": dim,
        "hidden": hidden,
        "iters": iters,
        "sample_every": sample_every,
        "n_devices": jax.device_count(),
        "device_kind": jax.devices()[0].device_kind,
    }


def _child_main(args):
    env_platform = os.environ.get("JAX_PLATFORMS", "")
    if args.platform == "cpu" or (
            args.platform is None and env_platform.startswith("cpu")):
        # fake the multi-chip world BEFORE backend init (same trick as
        # tests/conftest.py) so the step is a real sharded program
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count"
                        f"={args.devices}").strip()
    pin_platform(args.platform)
    result = run(batch=args.batch, dim=args.dim, hidden=args.hidden,
                 warmup=args.warmup, iters=args.iters,
                 rounds=args.rounds, sample_every=args.sample_every)
    print("BENCH_RESULT " + json.dumps(result))


def _parent_main(args):
    here = os.path.abspath(__file__)
    cmd = [sys.executable, here, "--child",
           "--batch", str(args.batch), "--dim", str(args.dim),
           "--hidden", str(args.hidden),
           "--warmup", str(args.warmup), "--iters", str(args.iters),
           "--rounds", str(args.rounds), "--devices", str(args.devices),
           "--sample-every", str(args.sample_every)]
    if args.platform:
        cmd += ["--platform", args.platform]
    return run_child_with_retries(
        cmd, os.path.dirname(here), args.timeouts, METRIC, UNIT,
        use_cache=args.platform is None,
        cache_match={"batch": args.batch, "dim": args.dim,
                     "hidden": args.hidden, "iters": args.iters},
        # an off/on overhead ratio: 1.0 is free, higher is overhead
        check=args.check, check_direction="lower")


def _parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--child", action="store_true")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--dim", type=int, default=512)
    p.add_argument("--hidden", type=int, default=2048)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iters", type=int, default=60,
                   help="timed updates per arm per round (sized so a "
                        "1%% bar is resolvable against host noise)")
    p.add_argument("--rounds", type=int, default=6,
                   help="order-alternating back-to-back arm pairs; "
                        "the reported value is the MEDIAN of the "
                        "per-round off/on ratios (more rounds = more "
                        "pairs for the median to discard the "
                        "burst-straddled ones)")
    p.add_argument("--sample-every", type=int, default=16,
                   help="memory-accountant sampling cadence in steps "
                        "on the ON arm (the statusz-scrape cadence)")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device count for the cpu platform")
    p.add_argument("--platform", default=None)
    p.add_argument("--check", action="store_true",
                   help="perf-regression sentinel: score the fresh "
                        "record against BENCH_MEASURED.json history "
                        "(exit 1 on a regression verdict)")
    p.add_argument("--timeouts", type=int, nargs="+", default=[480])
    return p.parse_args(argv)


if __name__ == "__main__":
    args = _parse_args(sys.argv[1:])
    if args.child:
        _child_main(args)
    else:
        sys.exit(_parent_main(args))
