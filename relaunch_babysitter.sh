#!/bin/bash
# Kill any running babysitter/probe and start a fresh one, detached.
# Run as `bash relaunch_babysitter.sh`.  Only processes whose comm is
# literally `python` are ever signaled: the agent-harness wrapper
# shells embed the full invoking command line (including these
# pattern strings), so a bare pkill -f self-matches and kills the
# invoker — which is exactly how three prior relaunch attempts died
# with exit 144.
cd "$(dirname "$0")"
kill_pythons_matching() {
    for pid in $(pgrep -f "$1"); do
        comm=$(cat "/proc/$pid/comm" 2>/dev/null)
        [ "$comm" = "python" ] && kill "$pid" 2>/dev/null
    done
}
kill_pythons_matching 'bench_session.py'
# probe + every battery child (bench.py, bench_transformer.py, ...) +
# hang_doctor probe children (python /tmp/tmpXXXX.py) — an orphaned
# one keeps holding the axon relay grant and contends with the fresh
# session's first probe
kill_pythons_matching 'bench[_.]'
kill_pythons_matching '/tmp/tmp.*\.py'
sleep 1
nohup python bench_session.py --max-hours "${1:-11}" >> bench_session.log 2>&1 &
echo "babysitter pid $!"
