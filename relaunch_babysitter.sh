#!/bin/bash
# Kill any running babysitter/probe and start a fresh one, detached.
# Run as `bash relaunch_babysitter.sh`.  Only processes whose comm is
# literally `python` are ever signaled: the agent-harness wrapper
# shells embed the full invoking command line (including these
# pattern strings), so a bare pkill -f self-matches and kills the
# invoker — which is exactly how three prior relaunch attempts died
# with exit 144.
cd "$(dirname "$0")"
kill_pythons_matching() {
    for pid in $(pgrep -f "$1"); do
        comm=$(cat "/proc/$pid/comm" 2>/dev/null)
        [ "$comm" = "python" ] && kill "$pid" 2>/dev/null
    done
}
descends_from_babysitter() {
    local pid=$1 i=0
    while [ "$pid" -gt 1 ] && [ $i -lt 20 ]; do
        grep -q 'bench_session\.py' "/proc/$pid/cmdline" 2>/dev/null \
            && return 0
        pid=$(awk '{print $4}' "/proc/$pid/stat" 2>/dev/null) || return 1
        [ -n "$pid" ] || return 1
        i=$((i + 1))
    done
    return 1
}
collect_babysitter_descendants() {
    # battery children (bench_*.py) and hang_doctor probe children
    # (python /tmp/hang_doctor_probe_*.py) — but ONLY those spawned by
    # a babysitter: a blanket bench_* kill once took out the operator's
    # own manual CPU measurement runs.  Collected BEFORE the parent
    # dies: killing bench_session first would reparent its children to
    # init and defeat the ancestry check.  Second clause: a child whose
    # babysitter ALREADY died sits reparented under init and may still
    # hold the axon relay grant, wedging the fresh session's first
    # probe — reap those too, but ONLY when the command line carries
    # this repo's marker: the hang_doctor_probe_ script prefix, this
    # repo's own battery scripts (bench_session spawns them by bare
    # name, `python bench_X.py`), or a path inside this repo.  A bare
    # /tmp/tmp*.py match once risked signaling unrelated Pythons on a
    # shared host.  CPU-pinned runs stay spared (the operator's manual
    # measurements carry "cpu" on their command line and cannot hold
    # the TPU).
    marker="hang_doctor_probe_|(^|[ /])(bench_[a-z0-9_]*|bench)\.py|$(pwd)/"
    for pid in $(pgrep -f "$1"); do
        comm=$(cat "/proc/$pid/comm" 2>/dev/null)
        [ "$comm" = "python" ] || continue
        if descends_from_babysitter "$pid"; then
            echo "$pid"
        else
            ppid=$(awk '{print $4}' "/proc/$pid/stat" 2>/dev/null)
            cmdline=$(tr '\0' ' ' < "/proc/$pid/cmdline" 2>/dev/null)
            if [ "$ppid" = "1" ] && \
               echo "$cmdline" | grep -Eq "$marker" && \
               ! echo "$cmdline" | grep -q 'cpu'; then
                echo "$pid"
            fi
        fi
    done
}
DOOMED=$(collect_babysitter_descendants 'bench[_.]'
         collect_babysitter_descendants 'hang_doctor_probe_.*\.py')
kill_pythons_matching 'bench_session.py'
for pid in $DOOMED; do kill "$pid" 2>/dev/null; done
sleep 1
nohup python bench_session.py --max-hours "${1:-11}" >> bench_session.log 2>&1 &
echo "babysitter pid $!"
