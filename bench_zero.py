"""Unified sharded-state benchmark: what does ZeRO-3 actually buy per
chip, and does the tuned layer-gather exchange win?

Two claims, one JSON line:

1. **Resident bytes per chip** — a transformer param tree is held two
   ways: pure DP (params, grads, and adam state replicated on every
   chip) and ZeRO-3 (``ShardedState.place`` + ``shard_opt_state`` +
   sharded grads — everything 1/world at rest).  Both are registered
   with the ``MemoryAccountant`` and SAMPLED, not asserted from
   arithmetic; ``value`` = DP bytes/chip ÷ ZeRO-3 bytes/chip (the
   ISSUE's acceptance floor is 2×; with every leaf dim-shardable it
   lands near the world size).
2. **Tuned vs worst exchange** — ``ShardedState.tune_gather_plan``
   searches the ``fsdp_gather`` plan-IR programs for this layout; the
   winner and the worst parity-clean candidate are re-timed fresh in
   the interleaved min-of-rounds harness (``exchange_speedup`` =
   worst / tuned, same discipline as bench_plan_ir).

The cache claim is asserted structurally: a second ``ShardedState``
tuning against the same scratch cache must come back ``from_cache=True``
with ``n_probes == 0`` and a bit-identical program.
"""

import argparse
import json
import os
import sys
import tempfile
import time

from _bench_common import pin_platform, run_child_with_retries

METRIC = "zero3_resident_bytes_reduction"
UNIT = "x"


def make_param_tree(rng, n_layers, d_model, vocab, dtype):
    """FULL (global) transformer-shaped params; every dim a multiple of
    the world so ``fsdp_dims`` shards every leaf."""
    def leaf(*shape):
        return rng.randn(*shape).astype(dtype) * 0.02

    tree = {"embed": leaf(vocab, d_model)}
    for i in range(n_layers):
        tree[f"layer_{i:02d}"] = {
            "wq": leaf(d_model, d_model), "wk": leaf(d_model, d_model),
            "wv": leaf(d_model, d_model), "wo": leaf(d_model, d_model),
            "w1": leaf(d_model, 4 * d_model),
            "w2": leaf(4 * d_model, d_model),
            "ln1": leaf(d_model), "ln2": leaf(d_model),
        }
    return tree


def _retime_arms(arms, rounds, iters):
    """Interleaved min-of-rounds over {name: (fn, data)} arms."""
    import jax

    for fn, data in arms.values():
        jax.block_until_ready(fn(data))          # compile + warm
    times = {name: float("inf") for name in arms}
    for _ in range(rounds):
        for name, (fn, data) in arms.items():
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(data)
            jax.block_until_ready(out)
            times[name] = min(times[name],
                              (time.perf_counter() - t0) / iters * 1e3)
    return times


def _measure_resident_bytes(comm, params, optimizer):
    """Accountant-sampled resident param+grad+opt bytes per chip for
    pure DP vs ZeRO-3 — the gauges /programz would show, not pencil
    arithmetic."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel.sharded_state import ShardedState
    from chainermn_tpu.training.optimizers import shard_opt_state
    from chainermn_tpu.utils.programs import MemoryAccountant

    n = comm.size
    acc = MemoryAccountant()

    sharded = ShardedState(params, comm)
    sharded.place(params)
    sharded.init_opt_state(optimizer)
    sharded.register_memory(acc, prefix="zero3")
    z3_grads = jax.tree.map(
        lambda p, s: jax.device_put(jnp.zeros_like(p),
                                    NamedSharding(comm.mesh, s)),
        params, sharded.specs)
    acc.register("zero3_grads", z3_grads)

    dp_params = jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(comm.mesh, P())),
        params)
    acc.register("dp_params", dp_params)
    acc.register("dp_opt_state", shard_opt_state(optimizer, dp_params))
    acc.register("dp_grads", jax.tree.map(jnp.zeros_like, dp_params))

    sample = acc.sample()
    z3 = sum(sample[k] for k in
             ("zero3_params", "zero3_opt_state", "zero3_grads")) / n
    dp = sum(sample[k] for k in
             ("dp_params", "dp_opt_state", "dp_grads")) / n
    # analytic per-chip claim off the layout table: params + opt state
    # (sharded.local_bytes) plus grads, which mirror the param layout
    predicted = sharded.local_bytes() + sum(
        l.local_bytes() for l in sharded.layouts()["params"])
    return sharded, dp, z3, predicted


def _race_exchange(comm, sharded, cache_path, *, trials, rounds, iters,
                   top_k):
    """Tune the layer-gather plan through the sharded-state surface,
    re-time tuned vs the worst parity-clean candidate, and assert the
    second tuning is 100% cache-served."""
    import numpy as np

    from chainermn_tpu.ops import plan_ir
    from chainermn_tpu.parallel.sharded_state import ShardedState
    from chainermn_tpu.utils import autotune

    t0 = time.perf_counter()
    plan = sharded.tune_gather_plan(comm, cache_path=cache_path,
                                    trials=trials, top_k=top_k)
    tune_s = time.perf_counter() - t0
    assert not plan.from_cache and plan.n_probes > 0
    ok = [t for t in plan.meta["timings"] if t["parity_ok"]]
    worst = max(ok, key=lambda t: t["ms"])

    by_label = {p.label: p for p in plan_ir.enumerate_pattern_programs(
        "fsdp_gather", wire_dtypes=(None,))}
    raw = autotune._probe_tree(sharded.local_template(), comm.size,
                               seed=1)
    data = autotune._place(raw, comm.mesh, (comm.axis_name,))

    def arm(program):
        return (autotune.build_pattern_probe_fn(
            comm.mesh, comm.axis_name, "fsdp_gather", program,
            dims=sharded.dims), data)

    times = _retime_arms(
        {"tuned": arm(plan_ir.ensure_program(plan, "fsdp_gather")),
         "worst": arm(by_label[worst["label"]])}, rounds, iters)

    again = ShardedState(sharded.params, comm).tune_gather_plan(
        comm, cache_path=cache_path, trials=trials, top_k=top_k)
    assert again.from_cache, "second tuning missed the plan cache"
    assert again.n_probes == 0, \
        f"cache hit still ran {again.n_probes} probes"
    assert again.program == plan.program, \
        "cached program differs from the tuned one"

    return {
        "speedup": times["worst"] / times["tuned"],
        "tuned_ms": times["tuned"],
        "worst_ms": times["worst"],
        "tuned_label": plan.strategy,
        "worst_label": worst["label"],
        "n_enumerated": plan.meta["n_enumerated"],
        "n_probed": plan.meta["n_probed"],
        "first_run_probes": plan.n_probes,
        "second_run_probes": again.n_probes,
        "second_run_cached": again.from_cache,
        "tune_seconds": tune_s,
    }


def run(n_layers=8, d_model=256, vocab=4096, trials=3, rounds=3,
        iters=3, top_k=6):
    import jax
    import numpy as np
    import optax

    import chainermn_tpu as cmn

    comm = cmn.create_communicator("tpu_xla")
    n = comm.size

    rng = np.random.RandomState(0)
    params = make_param_tree(rng, n_layers, d_model, vocab, np.float32)
    n_params = sum(l.size for l in jax.tree.leaves(params))

    sharded, dp_bytes, z3_bytes, predicted = _measure_resident_bytes(
        comm, params, optax.adam(1e-3))
    reduction = dp_bytes / z3_bytes
    assert reduction >= 2.0, (
        f"ZeRO-3 resident bytes/chip only {reduction:.2f}x below pure "
        f"DP — the sharded-state layer is not shedding state")

    cache_path = os.path.join(
        tempfile.mkdtemp(prefix="zero_bench_"), "plan_cache.json")
    race = _race_exchange(comm, sharded, cache_path, trials=trials,
                          rounds=rounds, iters=iters, top_k=top_k)

    result = {
        "metric": METRIC,
        "value": round(reduction, 3),
        "unit": UNIT,
        "vs_baseline": round(reduction, 3),
        "dp_bytes_per_chip": int(dp_bytes),
        "zero3_bytes_per_chip": int(z3_bytes),
        "zero3_predicted_bytes_per_chip": int(predicted),
        "exchange_speedup": round(race["speedup"], 3),
        "n_devices": n,
        "n_params": int(n_params),
        "model_config": f"{n_layers}x{d_model}x{vocab}",
        "device_kind": jax.devices()[0].device_kind,
    }
    for k in ("tuned_ms", "worst_ms", "tune_seconds"):
        result[f"exchange_{k}"] = round(race[k], 3)
    for k in ("tuned_label", "worst_label", "n_enumerated", "n_probed",
              "first_run_probes", "second_run_probes",
              "second_run_cached"):
        result[f"exchange_{k}"] = race[k]
    return result


def _child_main(args):
    env_platform = os.environ.get("JAX_PLATFORMS", "")
    if args.platform == "cpu" or (
            args.platform is None and env_platform.startswith("cpu")):
        # fake the multi-chip world BEFORE backend init (same trick as
        # tests/conftest.py) so the sharding is real, not size-1
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count"
                        f"={args.devices}").strip()
    pin_platform(args.platform)
    result = run(n_layers=args.n_layers, d_model=args.d_model,
                 vocab=args.vocab, trials=args.trials,
                 rounds=args.rounds, iters=args.iters, top_k=args.top_k)
    print("BENCH_RESULT " + json.dumps(result))


def _parent_main(args):
    here = os.path.abspath(__file__)
    cmd = [sys.executable, here, "--child",
           "--n-layers", str(args.n_layers),
           "--d-model", str(args.d_model), "--vocab", str(args.vocab),
           "--trials", str(args.trials), "--rounds", str(args.rounds),
           "--iters", str(args.iters), "--top-k", str(args.top_k),
           "--devices", str(args.devices)]
    if args.platform:
        cmd += ["--platform", args.platform]
    return run_child_with_retries(
        cmd, os.path.dirname(here), args.timeouts, METRIC, UNIT,
        use_cache=args.platform is None,
        cache_match={"model_config":
                     f"{args.n_layers}x{args.d_model}x{args.vocab}"},
        check=args.check)


def _parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--child", action="store_true")
    p.add_argument("--n-layers", type=int, default=8)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--vocab", type=int, default=4096)
    p.add_argument("--trials", type=int, default=3,
                   help="autotuner probe trials per candidate")
    p.add_argument("--rounds", type=int, default=3,
                   help="fresh re-time rounds (best round counts)")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--top-k", type=int, default=6,
                   help="candidates surviving cost-model pruning")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device count for --platform cpu")
    p.add_argument("--platform", default=None)
    p.add_argument("--timeouts", type=int, nargs="+", default=[480])
    p.add_argument("--check", action="store_true",
                   help="perf-regression sentinel: score the fresh "
                        "record against BENCH_MEASURED.json's prior "
                        "same-workload runs; the verdict rides the "
                        "JSON line under 'check' and the exit code is "
                        "1 on a regression verdict")
    return p.parse_args(argv)


if __name__ == "__main__":
    args = _parse_args(sys.argv[1:])
    if args.child:
        _child_main(args)
    else:
        sys.exit(_parent_main(args))
