"""Backward-overlapped vs window-end gradient exchange benchmark.

Both arms run the same microbatch stream through the same model on the
8-device mesh and differ only in the exchange lowering:

- **window** — the PR 4 shape: ``create_multi_node_optimizer()``
  defaults (fused dtype-grouped arena buckets), one window-end exchange
  whose arena concat JOINS every gradient leaf — the compiled schedule
  clusters the exchange collectives after the last backward op
  (``assert_overlap_collectives`` rejecting this arm is asserted below:
  a baseline that accidentally overlaps would void the measurement).
- **overlap** — ``overlap=True`` with a schedule-bearing plan: the
  schedule-aware AUTOTUNED one (``autotune_plan(overlap=True,
  t_bwd_s=<measured>)`` — bucket boundaries × eager/deferred ×
  rs-vs-ar per bucket, probed live, ranked by modeled exposed wire
  time under the measured backward) or the analytic leaf-aligned
  ``ar`` stream, whichever a short IN-STEP probe times faster —
  isolated probes cannot price the in-step cast/copy costs this
  backend exposes (XLA:CPU widens bf16 collectives to f32, so the
  "compressed" wire is pure cast overhead here), and the honest arm is
  the better of the two, with both timings recorded.  The winner's
  reverse-layer bucket stream fires under the backward pass
  (``assert_overlap_collectives`` passing this arm — with the
  schedule-position evidence and ``async_depth`` — is the overlap
  proof).

A synchronous-collective backend note, so the recorded number is read
for what it is: XLA:CPU emits no async start/done pairs
(``async_depth`` 0), every rank's thread executes its share of every
collective serially, and schedule position alone cannot hide wire
time the way a TPU's async collectives do.  What the CPU mesh DOES
measure is the lowering half of the win: the window-end arena pays a
pack + unpack copy of the whole gradient tree, while the overlap
stream's contiguous reverse-layer buckets ride leaf storage directly
— real steps/sec, biggest where the exchange dominates compute (the
default small-batch config).  The schedule half (wire under compute)
is what ``assert_overlap_collectives`` proves structurally.

The plan-cache round-trip is asserted for the schedule-bearing plan (a
second ``autotune_plan`` call must serve from cache with ZERO probes),
and a ``StragglerReport`` runs over each arm's timed spans so per-phase
skew rides the record alongside the throughput.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}:
value = overlap steps/sec ÷ window steps/sec (unit "x").  Same
hermetic child-process timeout/retry pattern as bench.py.
"""

import argparse
import json
import os
import sys
import tempfile
import time

from _bench_common import pin_platform, run_child_with_retries

METRIC = "overlap_exchange_speedup"
UNIT = "x"


def run(batch=8, dim=768, hidden=768, n_layers=8, classes=10,
        n_examples=4096, accum_steps=1, warmup=4, iters=24, rounds=3,
        trials=2, top_k=6, min_frac=0.5):
    import jax
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import (init_mlp, mlp_apply,
                                      softmax_cross_entropy)
    from chainermn_tpu.utils import (
        StragglerReport,
        TraceRecorder,
        assert_overlap_collectives,
        autotune_plan,
        set_recorder,
    )

    comm = cmn.create_communicator("tpu_xla")
    rng = np.random.RandomState(0)
    X = rng.randn(n_examples, dim).astype(np.float32)
    Y = (rng.rand(n_examples) * classes).astype(np.int32)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    params0 = init_mlp(jax.random.PRNGKey(0),
                       [dim] + [hidden] * n_layers + [classes])
    grad_bytes = sum(l.size * l.dtype.itemsize
                     for l in jax.tree.leaves(params0))

    def make(opt_kw):
        it = cmn.SerialIterator((X, Y), batch, shuffle=True, seed=11)
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm,
                                              **opt_kw)
        return cmn.StandardUpdater(it, opt, loss_fn, params0, comm,
                                   accum_steps=accum_steps)

    # -- hiding budget: measured wall time of the window arm's step --- #
    probe = make({})
    probe.update()                                  # compile
    jax.block_until_ready(probe.params)
    t0 = time.perf_counter()
    for _ in range(2):
        probe.update()
    jax.block_until_ready(probe.params)
    t_bwd_s = (time.perf_counter() - t0) / (2 * accum_steps)

    # -- schedule-aware autotune + plan-cache round-trip -------------- #
    cache = os.path.join(tempfile.mkdtemp(prefix="bench_overlap_"),
                         "plans.json")
    tuned = autotune_plan(comm, params0, overlap=True, t_bwd_s=t_bwd_s,
                          cache_path=cache, trials=trials, top_k=top_k)
    again = autotune_plan(comm, params0, overlap=True, t_bwd_s=t_bwd_s,
                          cache_path=cache, trials=trials, top_k=top_k)
    if not (again.from_cache and again.n_probes == 0
            and again.schedule == tuned.schedule):
        raise AssertionError(
            f"schedule-bearing plan did not round-trip the cache: "
            f"from_cache={again.from_cache} n_probes={again.n_probes}")

    # -- in-step selection: tuned plan vs analytic leaf-aligned stream  #
    from chainermn_tpu.ops.fused import build_overlap_schedule
    from chainermn_tpu.utils.autotune import Plan

    max_leaf = max(l.size * l.dtype.itemsize
                   for l in jax.tree.leaves(params0))
    analytic = Plan(
        strategy="overlap", bucket_bytes=max_leaf,
        schedule=[dict(e, via="ar") for e in
                  build_overlap_schedule(params0, max_leaf)])

    def quick_steps(plan_arm):
        upd = make({"plan": plan_arm, "overlap": True})
        for _ in range(2):
            upd.update()
        jax.block_until_ready(upd.params)
        q = max(4, iters // 4)
        t0 = time.perf_counter()
        for _ in range(q):
            upd.update()
        jax.block_until_ready(upd.params)
        return q * accum_steps / (time.perf_counter() - t0)

    quick = {"tuned": quick_steps(tuned),
             "analytic_leaf_stream": quick_steps(analytic)}
    plan_source = max(quick, key=quick.get)
    plan = tuned if plan_source == "tuned" else analytic

    # -- proofs: overlap arm overlaps, window arm does NOT ------------ #
    def compile_window(upd):
        arrays, _k, _tail = upd._assemble_host_window()
        fn = upd._get_step(len(arrays), 1, accum_steps)
        carry = (upd.params, upd.state, upd.opt_state)
        return fn.lower(carry, *arrays).compile()

    overlap_kw = {"plan": plan, "overlap": True}
    rep = assert_overlap_collectives(compile_window(make(overlap_kw)),
                                     min_frac=min_frac)
    # the baseline's fraction is REPORTED, not gated: under an accum
    # scan it is structurally 0 (every backward dot lives in the while
    # body), but at accum_steps=1 XLA's slice-of-concat simplification
    # can partially un-join the arena and overlap some buckets on its
    # own — that is the real PR 4 baseline, and hiding it would
    # overstate the win
    base_rep = assert_overlap_collectives(compile_window(make({})),
                                          min_frac=0.0)

    # -- timing: interleaved rounds, best-of, skew recorded ----------- #
    recorder = TraceRecorder(capacity=1 << 16, enabled=True,
                             rank=getattr(comm, "rank", 0))
    prev = set_recorder(recorder)
    straggler = StragglerReport(comm, recorder=recorder, write=False)
    skew = {}
    try:
        def timed_arm(name, opt_kw):
            upd = make(opt_kw)
            for _ in range(warmup):
                upd.update()
            jax.block_until_ready(upd.params)
            recorder.drain_phase_stats(None)        # fresh interval
            start_iter = upd.iteration
            t0 = time.perf_counter()
            for _ in range(iters):
                upd.update()
            jax.block_until_ready(upd.params)
            dt = time.perf_counter() - t0
            straggler(None)
            skew[name] = straggler.last_report["max_skew"]
            return (upd.iteration - start_iter) / dt

        best = {"window": 0.0, "overlap": 0.0}
        for _ in range(rounds):
            best["window"] = max(best["window"],
                                 timed_arm("window", {}))
            best["overlap"] = max(best["overlap"],
                                  timed_arm("overlap", overlap_kw))
    finally:
        set_recorder(prev)

    speedup = best["overlap"] / best["window"]
    return {
        "metric": METRIC,
        "value": round(speedup, 3),
        "unit": UNIT,
        "vs_baseline": round(speedup, 3),
        "window_steps_per_s": round(best["window"], 2),
        "overlap_steps_per_s": round(best["overlap"], 2),
        "overlap_proof": {k: round(v, 4) if isinstance(v, float) else v
                          for k, v in rep.items()},
        "window_end_frac": round(base_rep["frac"], 4),
        "plan": {
            "source": plan_source,
            "strategy": plan.strategy,
            "bucket_bytes": plan.bucket_bytes,
            "wire_dtype": plan.wire_dtype,
            "n_buckets": len(plan.schedule or []),
            "modes": [e["mode"] for e in plan.schedule or []],
            "via": [e["via"] for e in plan.schedule or []],
        },
        "in_step_probe_steps_per_s": {k: round(v, 2)
                                      for k, v in quick.items()},
        "plan_cache_roundtrip": True,
        "t_bwd_s": round(t_bwd_s, 5),
        "straggler_skew": {k: round(v, 4) for k, v in skew.items()},
        "grad_bytes": grad_bytes,
        "accum_steps": accum_steps,
        "batch": batch,
        "dim": dim,
        "hidden": hidden,
        "n_layers": n_layers,
        "n_devices": jax.device_count(),
        "device_kind": jax.devices()[0].device_kind,
    }


def _child_main(args):
    env_platform = os.environ.get("JAX_PLATFORMS", "")
    if args.platform == "cpu" or (
            args.platform is None and env_platform.startswith("cpu")):
        # fake the multi-chip world BEFORE backend init (same trick as
        # tests/conftest.py) so the exchange is real, not size-1
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count"
                        f"={args.devices}").strip()
    pin_platform(args.platform)
    result = run(batch=args.batch, dim=args.dim, hidden=args.hidden,
                 n_layers=args.n_layers, accum_steps=args.accum_steps,
                 warmup=args.warmup, iters=args.iters,
                 rounds=args.rounds, trials=args.trials,
                 top_k=args.top_k, min_frac=args.min_frac)
    print("BENCH_RESULT " + json.dumps(result))


def _parent_main(args):
    here = os.path.abspath(__file__)
    cmd = [sys.executable, here, "--child",
           "--batch", str(args.batch), "--dim", str(args.dim),
           "--hidden", str(args.hidden),
           "--n-layers", str(args.n_layers),
           "--accum-steps", str(args.accum_steps),
           "--warmup", str(args.warmup), "--iters", str(args.iters),
           "--rounds", str(args.rounds), "--trials", str(args.trials),
           "--top-k", str(args.top_k),
           "--min-frac", str(args.min_frac),
           "--devices", str(args.devices)]
    if args.platform:
        cmd += ["--platform", args.platform]
    return run_child_with_retries(
        cmd, os.path.dirname(here), args.timeouts, METRIC, UNIT,
        use_cache=args.platform is None,
        cache_match={"batch": args.batch, "dim": args.dim,
                     "hidden": args.hidden, "n_layers": args.n_layers,
                     "accum_steps": args.accum_steps})


def _parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--child", action="store_true")
    p.add_argument("--batch", type=int, default=8,
                   help="1 example/device: the exchange-dominated "
                        "regime where the lowering difference is "
                        "what's measured")
    p.add_argument("--dim", type=int, default=768)
    p.add_argument("--hidden", type=int, default=768,
                   help="sub-arena-bucket layer width: every leaf "
                        "rides the window arm's arena, so the baseline "
                        "really is the clustered window-end join")
    p.add_argument("--n-layers", type=int, default=8)
    p.add_argument("--accum-steps", type=int, default=1,
                   help="microbatches per window (the peel regime; "
                        "bench_accum.py owns the M-amortisation claim)")
    p.add_argument("--warmup", type=int, default=4)
    p.add_argument("--iters", type=int, default=24,
                   help="timed updates per round per arm")
    p.add_argument("--rounds", type=int, default=3,
                   help="interleaved timing rounds (best round counts)")
    p.add_argument("--trials", type=int, default=2,
                   help="autotune probe repetitions per candidate")
    p.add_argument("--top-k", type=int, default=6)
    p.add_argument("--min-frac", type=float, default=0.5,
                   help="overlap-proof floor: fraction of exchange "
                        "collectives that must start inside the "
                        "backward region")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device count for the cpu platform")
    p.add_argument("--platform", default=None)
    p.add_argument("--timeouts", type=int, nargs="+", default=[480])
    return p.parse_args(argv)


if __name__ == "__main__":
    args = _parse_args(sys.argv[1:])
    if args.child:
        _child_main(args)
    else:
        sys.exit(_parent_main(args))
